"""Flash attention for TPU (Pallas) — forward AND backward kernels.

Tiled online-softmax attention. Layout [B,S,H,D] -> [B*H, S, D]; the grid
streams Q and K/V blocks so nothing larger than a block is VMEM-resident
(the round-1 kernel kept whole K/V per head in VMEM, capping sequence
length). bf16 inputs feed the MXU directly (preferred_element_type=f32
accumulate); all softmax state is f32 on the VPU — the standard TPU recipe
(pallas_guide.md: MXU matmuls with preferred_element_type; min tile
(16,128) for bf16).

Forward saves the logsumexp per row; backward is two Pallas kernels that
recompute probabilities from (q, k, lse) inside the kernel — dq in one
pass over K blocks, dk/dv in one pass over Q blocks — with f32 scratch
accumulators. Causal masking skips fully-masked blocks via a predicate on
the grid position, halving FLOPs for autoregressive models.

Reference capability (not design): the reference has no first-party
attention kernels at all (torch/NCCL stack); this is new TPU-native work
per SURVEY.md §5.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .attention import mha_reference

_NEG_INF = -1e30


def _use_interpret() -> bool:
    return jax.default_backend() != "tpu"


def _fit_block(block: int, seq: int) -> int:
    """Largest multiple of 128 that is <= block and divides seq. The
    kernel path requires seq % 128 == 0 (flash_attention routes anything
    else to mha_reference), so a 128-multiple divisor always exists —
    sub-128 blocks would lower to illegal / silently padded Mosaic tiles
    on real TPU."""
    block = min(block, seq)
    if seq % block == 0:
        return block
    for b in range(block - block % 128, 127, -128):
        if seq % b == 0:
            return b
    return 128


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                m_scr, l_scr, acc_scr, *,
                sm_scale: float, causal: bool,
                block_q: int, block_k: int, num_kb: int):
    """Grid: (B*H, num_q_blocks, num_k_blocks); K innermost so the f32
    scratch (m, l, acc) carries across K iterations for one Q block."""
    qi = pl.program_id(1)
    kb = pl.program_id(2)

    @pl.when(kb == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    # Causal: the block [qi*bq, qi*bq+bq) x [kb*bk, kb*bk+bk) intersects the
    # lower triangle iff its last row can see its first column.
    run = (qi * block_q + block_q - 1 >= kb * block_k) if causal else True

    @pl.when(run)
    def _compute():
        q = q_ref[...]  # (block_q, d) input dtype — MXU fast path
        k = k_ref[...]
        v = v_ref[...]
        # scale the (block_q, d) tile, not the (block_q, block_k) s matrix
        s = jax.lax.dot_general(
            q * jnp.asarray(sm_scale, q.dtype), k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if causal:
            rows = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows >= cols, s, _NEG_INF)
        m_prev = m_scr[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_scr[...] = l_scr[...] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[...] = acc_scr[...] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[...] = m_new

    @pl.when(kb == num_kb - 1)
    def _finalize():
        l = jnp.maximum(l_scr[...], 1e-30)
        o_ref[...] = (acc_scr[...] / l).astype(o_ref.dtype)
        lse_ref[...] = (m_scr[...] + jnp.log(l)).T


def _fwd_single_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *,
                       sm_scale: float, causal: bool, block_q: int,
                       block_k: int):
    """Single-K-block forward (S <= block_k): direct one-shot softmax, no
    online-softmax scratch carry / rescale passes."""
    qi = pl.program_id(1)
    q = q_ref[...]
    k = k_ref[...]
    v = v_ref[...]
    s = jax.lax.dot_general(
        q * jnp.asarray(sm_scale, q.dtype), k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    if causal:
        rows = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        cols = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
        s = jnp.where(rows >= cols, s, _NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    l = jnp.maximum(jnp.sum(p, axis=-1, keepdims=True), 1e-30)
    acc = jax.lax.dot_general(
        p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    o_ref[...] = (acc / l).astype(o_ref.dtype)
    lse_ref[...] = (m + jnp.log(l)).T


def _flash_fwd(q, k, v, sm_scale, causal, block_q, block_k):
    """[B*H, S, D] in -> (out [B*H, S, D], lse [B*H, S])."""
    bh, seq_q, d = q.shape
    seq_k = k.shape[1]
    block_q = _fit_block(block_q, seq_q)
    block_k = _fit_block(block_k, seq_k)
    num_kb = seq_k // block_k
    from jax.experimental.pallas import tpu as pltpu

    from ..jax_compat import tpu_compiler_params as _compiler_params

    if num_kb == 1:
        kernel = functools.partial(
            _fwd_single_kernel, sm_scale=sm_scale, causal=causal,
            block_q=block_q, block_k=block_k)
        out, lse = pl.pallas_call(
            kernel,
            grid=(bh, seq_q // block_q),
            in_specs=[
                pl.BlockSpec((None, block_q, d), lambda b, i: (b, i, 0)),
                pl.BlockSpec((None, block_k, d), lambda b, i: (b, 0, 0)),
                pl.BlockSpec((None, block_k, d), lambda b, i: (b, 0, 0)),
            ],
            out_specs=[
                pl.BlockSpec((None, block_q, d), lambda b, i: (b, i, 0)),
                pl.BlockSpec((None, 1, block_q), lambda b, i: (b, 0, i)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((bh, seq_q, d), q.dtype),
                # [bh, 1, S]: q-positions on the LANE axis. A trailing
                # singleton dim ([bh, S, 1]) would tile-pad 128x in HBM
                # (1.5 MB -> 192 MB per layer) and dominate the step in
                # residual-stacking copies; this layout pads 8x only.
                jax.ShapeDtypeStruct((bh, 1, seq_q), jnp.float32),
            ],
            compiler_params=_compiler_params(
                dimension_semantics=("parallel", "arbitrary")),
            interpret=_use_interpret(),
            cost_estimate=pl.CostEstimate(
                flops=4 * bh * seq_q * seq_k * d // (2 if causal else 1),
                bytes_accessed=(q.size + k.size + v.size) * q.dtype.itemsize,
                transcendentals=bh * seq_q * seq_k,
            ),
        )(q, k, v)
        return out, lse

    kernel = functools.partial(
        _fwd_kernel, sm_scale=sm_scale, causal=causal,
        block_q=block_q, block_k=block_k, num_kb=num_kb)
    grid = (bh, seq_q // block_q, num_kb)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((None, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((None, block_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((None, 1, block_q), lambda b, i, j: (b, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, seq_q, d), q.dtype),
            # [bh, 1, S]: see _fwd_single_kernel's out_shape comment
            jax.ShapeDtypeStruct((bh, 1, seq_q), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        # batch*head and q-block grid dims are independent — marking them
        # parallel lets Mosaic pipeline the next block's DMA under compute;
        # only the K dim (scratch carry) is sequential
        compiler_params=_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=_use_interpret(),
        cost_estimate=pl.CostEstimate(
            flops=4 * bh * seq_q * seq_k * d // (2 if causal else 1),
            bytes_accessed=(q.size + k.size + v.size) * q.dtype.itemsize,
            transcendentals=bh * seq_q * seq_k,
        ),
    )(q, k, v)
    return out, lse


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------


def _bwd_fused_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                      dq_ref, dk_ref, dv_ref, dk_scr, dv_scr, *,
                      sm_scale: float, causal: bool,
                      block_q: int, block_k: int, num_qb: int):
    """Single-pass backward for the num_kb == 1 case (S <= block_k): one
    (b, qi) instance computes s/p ONCE and emits dq directly plus dk/dv
    scratch accumulation — versus the two-pass scheme which recomputes
    the s matrix, causal mask, and exp in both the dq and dkv kernels.
    Grid: (B*H, 1, num_q_blocks); qi minor so dk/dv carry in scratch."""
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    q = q_ref[...]
    k = k_ref[...]
    v = v_ref[...]
    do = do_ref[...]
    lse = lse_ref[...].T    # stored [1, block_q]; rows here are q-positions
    delta = delta_ref[...].T
    # scale on the (block_q, d) tile — 16x cheaper than scaling the
    # (block_q, block_k) s matrix
    qs = q * jnp.asarray(sm_scale, q.dtype)
    s = jax.lax.dot_general(
        qs, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    if causal:
        rows = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        cols = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
        s = jnp.where(rows >= cols, s, _NEG_INF)
    p = jnp.exp(s - lse)
    pt = p.astype(do.dtype)
    dv_scr[...] += jax.lax.dot_general(
        pt, do, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    dp = jax.lax.dot_general(
        do, v, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    # ds = dL/ds; the sm_scale factor of s = (q·scale)·kᵀ routes into both
    # dq and dk, so fold it once here
    dsc = (p * (dp - delta) * sm_scale).astype(k.dtype)
    dq_ref[...] = jax.lax.dot_general(
        dsc, k, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32).astype(dq_ref.dtype)
    dk_scr[...] += jax.lax.dot_general(
        dsc, q, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(qi == num_qb - 1)
    def _finalize():
        dk_ref[...] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[...] = dv_scr[...].astype(dv_ref.dtype)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   dq_scr, *, sm_scale: float, causal: bool,
                   block_q: int, block_k: int, num_kb: int):
    """Grid: (B*H, num_q_blocks, num_k_blocks); accumulates dq over K."""
    qi = pl.program_id(1)
    kb = pl.program_id(2)

    @pl.when(kb == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    run = (qi * block_q + block_q - 1 >= kb * block_k) if causal else True

    @pl.when(run)
    def _compute():
        q = q_ref[...]
        k = k_ref[...]
        v = v_ref[...]
        do = do_ref[...]
        lse = lse_ref[...].T
        delta = delta_ref[...].T
        s = jax.lax.dot_general(
            q * jnp.asarray(sm_scale, q.dtype), k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if causal:
            rows = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows >= cols, s, _NEG_INF)
        p = jnp.exp(s - lse)  # (bq, bk) f32, exactly softmax(s)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * sm_scale
        dq_scr[...] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(kb == num_kb - 1)
    def _finalize():
        dq_ref[...] = dq_scr[...].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_scr, dv_scr, *,
                    sm_scale: float, causal: bool,
                    block_q: int, block_k: int, num_qb: int):
    """Grid: (B*H, num_k_blocks, num_q_blocks); accumulates dk/dv over Q."""
    kb = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    run = (qi * block_q + block_q - 1 >= kb * block_k) if causal else True

    @pl.when(run)
    def _compute():
        q = q_ref[...]
        k = k_ref[...]
        v = v_ref[...]
        do = do_ref[...]
        lse = lse_ref[...].T
        delta = delta_ref[...].T
        s = jax.lax.dot_general(
            q * jnp.asarray(sm_scale, q.dtype), k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if causal:
            rows = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows >= cols, s, _NEG_INF)
        p = jnp.exp(s - lse)
        pt = p.astype(do.dtype)
        dv_scr[...] += jax.lax.dot_general(
            pt, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * sm_scale
        dk_scr[...] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(qi == num_qb - 1)
    def _finalize():
        dk_ref[...] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[...] = dv_scr[...].astype(dv_ref.dtype)


def _flash_bwd(q, k, v, o, lse, g, sm_scale, causal, block_q, block_k):
    bh, seq_q, d = q.shape
    seq_k = k.shape[1]
    block_q = _fit_block(block_q, seq_q)
    block_k = _fit_block(block_k, seq_k)
    num_qb = seq_q // block_q
    num_kb = seq_k // block_k
    # delta_i = rowsum(dO_i * O_i): cheap elementwise reduce — jnp/XLA.
    # [bh, 1, S] like lse (a trailing dim would tile-pad 128x in HBM).
    delta = jnp.sum(g.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1)[:, None, :]

    interp = _use_interpret()
    from jax.experimental.pallas import tpu as pltpu

    from ..jax_compat import tpu_compiler_params as _compiler_params

    if num_kb == 1:
        # single K block: one fused pass computes s/p once and emits
        # dq + dk + dv together (the two-pass scheme below recomputes the
        # s matrix, mask, and exp in each kernel)
        qb_spec = pl.BlockSpec((None, block_q, d), lambda b, j, i: (b, i, 0))
        rowb_spec = pl.BlockSpec((None, 1, block_q),
                                 lambda b, j, i: (b, 0, i))
        kb_spec = pl.BlockSpec((None, block_k, d), lambda b, j, i: (b, j, 0))
        dq, dk, dv = pl.pallas_call(
            functools.partial(
                _bwd_fused_kernel, sm_scale=sm_scale, causal=causal,
                block_q=block_q, block_k=block_k, num_qb=num_qb),
            grid=(bh, 1, num_qb),
            in_specs=[qb_spec, kb_spec, kb_spec, qb_spec, rowb_spec,
                      rowb_spec],
            out_specs=[qb_spec, kb_spec, kb_spec],
            out_shape=[
                jax.ShapeDtypeStruct((bh, seq_q, d), q.dtype),
                jax.ShapeDtypeStruct((bh, seq_k, d), k.dtype),
                jax.ShapeDtypeStruct((bh, seq_k, d), v.dtype),
            ],
            scratch_shapes=[
                pltpu.VMEM((block_k, d), jnp.float32),
                pltpu.VMEM((block_k, d), jnp.float32),
            ],
            compiler_params=_compiler_params(
                dimension_semantics=("parallel", "arbitrary", "arbitrary")),
            interpret=interp,
            cost_estimate=pl.CostEstimate(
                flops=10 * bh * seq_q * seq_k * d // (2 if causal else 1),
                bytes_accessed=(q.size * 2 + k.size * 2 + v.size * 2)
                * q.dtype.itemsize,
                transcendentals=bh * seq_q * seq_k,
            ),
        )(q, k, v, g, lse, delta)
        return dq, dk, dv

    q_spec = pl.BlockSpec((None, block_q, d), lambda b, i, j: (b, i, 0))
    row_spec = pl.BlockSpec((None, 1, block_q), lambda b, i, j: (b, 0, i))
    k_spec = pl.BlockSpec((None, block_k, d), lambda b, i, j: (b, j, 0))

    dq = pl.pallas_call(
        functools.partial(
            _bwd_dq_kernel, sm_scale=sm_scale, causal=causal,
            block_q=block_q, block_k=block_k, num_kb=num_kb),
        grid=(bh, num_qb, num_kb),
        in_specs=[q_spec, k_spec, k_spec, q_spec, row_spec, row_spec],
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct((bh, seq_q, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interp,
        cost_estimate=pl.CostEstimate(
            flops=4 * bh * seq_q * seq_k * d // (2 if causal else 1),
            bytes_accessed=(q.size * 2 + k.size + v.size) * q.dtype.itemsize,
            transcendentals=bh * seq_q * seq_k,
        ),
    )(q, k, v, g, lse, delta)

    # dk/dv: Q streams in the minor grid dim.
    qb_spec = pl.BlockSpec((None, block_q, d), lambda b, j, i: (b, i, 0))
    rowb_spec = pl.BlockSpec((None, 1, block_q), lambda b, j, i: (b, 0, i))
    kb_spec = pl.BlockSpec((None, block_k, d), lambda b, j, i: (b, j, 0))
    dk, dv = pl.pallas_call(
        functools.partial(
            _bwd_dkv_kernel, sm_scale=sm_scale, causal=causal,
            block_q=block_q, block_k=block_k, num_qb=num_qb),
        grid=(bh, num_kb, num_qb),
        in_specs=[qb_spec, kb_spec, kb_spec, qb_spec, rowb_spec, rowb_spec],
        out_specs=[kb_spec, kb_spec],
        out_shape=[
            jax.ShapeDtypeStruct((bh, seq_k, d), k.dtype),
            jax.ShapeDtypeStruct((bh, seq_k, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        compiler_params=_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interp,
        cost_estimate=pl.CostEstimate(
            flops=8 * bh * seq_q * seq_k * d // (2 if causal else 1),
            bytes_accessed=(q.size * 2 + k.size * 2 + v.size * 2)
            * q.dtype.itemsize,
            transcendentals=bh * seq_q * seq_k,
        ),
    )(q, k, v, g, lse, delta)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# custom VJP — boundary carries MERGED [B, S, H*D] tensors
# ---------------------------------------------------------------------------
# Residuals cross the fwd/bwd boundary in merged form on purpose: a
# [B*H, S, 64] tensor tile-pads its 64-lane minor dim to 128 in HBM (2x
# memory AND 2x traffic every time the remat machinery stacks it into the
# per-layer residual buffers). [B, S, 768] is unpadded; the padded kernel
# layout exists only transiently inside the fwd/bwd computations.


def _to_bhsd(x):
    b, s, h, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b * h, s, d)


def _from_bhsd(x, b, h):
    bh, s, d = x.shape
    return x.reshape(b, h, s, d).transpose(0, 2, 1, 3)


def _merged_to_bhsd(x, h):
    b, s, hd = x.shape
    return _to_bhsd(x.reshape(b, s, h, hd // h))


def _bhsd_to_merged(x, b, h):
    s, d = x.shape[1:]
    return _from_bhsd(x, b, h).reshape(b, s, h * d)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(qm, km, vm, h, sm_scale, causal, block_q, block_k):
    out, _ = _flash_fwd(_merged_to_bhsd(qm, h), _merged_to_bhsd(km, h),
                        _merged_to_bhsd(vm, h), sm_scale, causal,
                        block_q, block_k)
    return _bhsd_to_merged(out, qm.shape[0], h)


def _flash_vjp_fwd(qm, km, vm, h, sm_scale, causal, block_q, block_k):
    from jax.ad_checkpoint import checkpoint_name

    out, lse = _flash_fwd(_merged_to_bhsd(qm, h), _merged_to_bhsd(km, h),
                          _merged_to_bhsd(vm, h), sm_scale, causal,
                          block_q, block_k)
    # Named so a remat policy can choose to SAVE these residuals: pallas
    # outputs are not dots, so a dots-saveable policy would otherwise
    # re-run the forward kernel inside the backward pass.
    out_m = checkpoint_name(_bhsd_to_merged(out, qm.shape[0], h), "flash_out")
    lse = checkpoint_name(lse, "flash_lse")
    return out_m, (qm, km, vm, out_m, lse)


def _flash_vjp_bwd(h, sm_scale, causal, block_q, block_k, res, g):
    qm, km, vm, out_m, lse = res
    b = qm.shape[0]
    dq, dk, dv = _flash_bwd(
        _merged_to_bhsd(qm, h), _merged_to_bhsd(km, h),
        _merged_to_bhsd(vm, h), _merged_to_bhsd(out_m, h), lse,
        _merged_to_bhsd(g, h), sm_scale, causal, block_q, block_k)
    return (_bhsd_to_merged(dq, b, h), _bhsd_to_merged(dk, b, h),
            _bhsd_to_merged(dv, b, h))


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    causal: bool = True,
                    sm_scale: Optional[float] = None,
                    block_q: int = 1024, block_k: int = 1024) -> jax.Array:
    """Flash attention. q/k/v: [batch, seq, heads, head_dim] -> same shape.

    head_dim should be a multiple of 128 for MXU efficiency (pads are the
    caller's job — model dims are chosen MXU-friendly instead)."""
    if sm_scale is None:
        sm_scale = 1.0 / (q.shape[-1] ** 0.5)
    if causal and q.shape[1] != k.shape[1]:
        # The kernels' diagonal masks assume square attention; the reference
        # formulation applies a (seq_k - seq_q) offset this path does not.
        raise ValueError(
            f"causal flash_attention requires seq_q == seq_k, got "
            f"{q.shape[1]} != {k.shape[1]}; use mha_reference for "
            "offset-causal decode")
    if q.shape[1] % 128 != 0 or k.shape[1] % 128 != 0:
        # Mosaic's minimum tile is (8, 128): sub-128 sequence blocks lower
        # to illegal or silently padded tiles on real TPU. Pads are the
        # caller's job; unpadded odd shapes go to the XLA reference.
        return mha_reference(q, k, v, causal=causal, sm_scale=sm_scale)
    b, s, h, d = q.shape
    merge = lambda x: x.reshape(x.shape[0], x.shape[1], h * d)  # noqa: E731
    out = _flash(merge(q), merge(k), merge(v), h, sm_scale, causal,
                 block_q, block_k)
    return out.reshape(b, s, h, d)
