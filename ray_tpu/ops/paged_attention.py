"""Paged KV-cache primitives for the continuous-batching LLM engine.

The serving-side analog of vLLM's PagedAttention (PAPERS.md): the KV
cache is a pool of fixed-size blocks ``[num_blocks, block_size, KH, hd]``
shared by every resident sequence, and each sequence addresses its
context through a *block table* — a row of block ids. Three shape-static
primitives cover the whole lifecycle, so XLA compiles exactly one decode
program regardless of which sequences are live:

- :func:`paged_write_step` scatters one new (K, V) per batch slot at its
  sequence position (decode iteration).
- :func:`paged_write_prefill` scatters a whole prompt's (K, V) into the
  blocks named by one block-table row (bucketed prefill).
- :func:`paged_attention_decode` attends one query token per slot over
  the gathered, length-masked paged context.

Inactive slots / padded positions are routed out-of-bounds and dropped
(``mode="drop"``), so garbage slots never corrupt pool blocks owned by
other sequences. All attention math runs in f32 (matches mha_reference).
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

_NEG_INF = -1e30


def paged_gather_kv(cache: jax.Array, block_rows: jax.Array) -> jax.Array:
    """Gather per-sequence context from the pool.

    cache: [N, Bs, KH, hd]; block_rows: [B, M] int32 (unused entries may
    be any value — callers mask by length). Returns [B, M*Bs, KH, hd].
    """
    b, m = block_rows.shape
    _, bs, kh, hd = cache.shape
    # clip (jnp default) is fine here: out-of-range rows gather garbage
    # that the caller's length mask removes before the softmax
    gathered = cache[jnp.clip(block_rows, 0, cache.shape[0] - 1)]
    return gathered.reshape(b, m * bs, kh, hd)


def paged_write_step(cache: jax.Array, block_rows: jax.Array,
                     positions: jax.Array, new: jax.Array,
                     active: jax.Array) -> jax.Array:
    """Scatter one token's K (or V) per batch slot into the pool.

    cache: [N, Bs, KH, hd]; block_rows: [B, M]; positions: [B] (the
    sequence index being written); new: [B, KH, hd]; active: [B] bool.
    Inactive slots are dropped (scattered out of bounds), so a padded
    slot can never clobber a block owned by a live sequence.
    """
    n, bs = cache.shape[0], cache.shape[1]
    b = positions.shape[0]
    m = block_rows.shape[1]
    block_idx = jnp.clip(positions // bs, 0, m - 1)
    bids = block_rows[jnp.arange(b), block_idx]
    bids = jnp.where(active, bids, n)  # out of bounds -> dropped
    return cache.at[bids, positions % bs].set(
        new.astype(cache.dtype), mode="drop")


def paged_write_prefill(cache: jax.Array, block_row: jax.Array,
                        seq: jax.Array, length: jax.Array) -> jax.Array:
    """Scatter a prompt's K (or V) sequence into one block-table row.

    cache: [N, Bs, KH, hd]; block_row: [M]; seq: [S, KH, hd] (S is the
    static prefill bucket); length: scalar int32 — positions >= length
    are padding and dropped.
    """
    n, bs = cache.shape[0], cache.shape[1]
    s = seq.shape[0]
    pos = jnp.arange(s)
    bids = block_row[jnp.clip(pos // bs, 0, block_row.shape[0] - 1)]
    bids = jnp.where(pos < length, bids, n)  # pad -> dropped
    return cache.at[bids, pos % bs].set(seq.astype(cache.dtype),
                                        mode="drop")


def paged_attention_decode(q: jax.Array, k_cache: jax.Array,
                           v_cache: jax.Array, block_rows: jax.Array,
                           lengths: jax.Array) -> jax.Array:
    """One-token-per-slot attention over the paged context.

    q: [B, H, hd]; k_cache/v_cache: [N, Bs, KH, hd]; block_rows: [B, M];
    lengths: [B] — number of valid context positions (INCLUDING the
    token just written this step). GQA (KH < H) broadcasts KV heads.
    Returns [B, H, hd] in q's dtype; math in f32.
    """
    b, h, hd = q.shape
    kh = k_cache.shape[2]
    k = paged_gather_kv(k_cache, block_rows)  # [B, S, KH, hd]
    v = paged_gather_kv(v_cache, block_rows)
    if kh != h:
        rep = h // kh
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    s = k.shape[1]
    scores = jnp.einsum("bhd,bshd->bhs", q.astype(jnp.float32),
                        k.astype(jnp.float32)) / math.sqrt(hd)
    mask = jnp.arange(s)[None, :] < lengths[:, None]          # [B, S]
    scores = jnp.where(mask[:, None, :], scores, _NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhs,bshd->bhd", probs, v.astype(jnp.float32))
    return out.astype(q.dtype)
