"""Paged KV-cache primitives for the continuous-batching LLM engine.

The serving-side analog of vLLM's PagedAttention (PAPERS.md): the KV
cache is a pool of fixed-size blocks ``[num_blocks, block_size, KH, hd]``
shared by every resident sequence, and each sequence addresses its
context through a *block table* — a row of block ids. Three shape-static
primitives cover the whole lifecycle, so XLA compiles exactly one decode
program regardless of which sequences are live:

- :func:`paged_write_step` scatters one new (K, V) per batch slot at its
  sequence position (decode iteration).
- :func:`paged_write_prefill` scatters a whole prompt's (K, V) into the
  blocks named by one block-table row (bucketed prefill).
- :func:`paged_attention_decode` attends one query token per slot over
  the gathered, length-masked paged context.

Inactive slots / padded positions are routed out-of-bounds and dropped
(``mode="drop"``), so garbage slots never corrupt pool blocks owned by
other sequences. All attention math runs in f32 (matches mha_reference).
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

_NEG_INF = -1e30


def paged_gather_kv(cache: jax.Array, block_rows: jax.Array) -> jax.Array:
    """Gather per-sequence context from the pool.

    cache: [N, Bs, KH, hd]; block_rows: [B, M] int32 (unused entries may
    be any value — callers mask by length). Returns [B, M*Bs, KH, hd].
    """
    b, m = block_rows.shape
    _, bs, kh, hd = cache.shape
    # clip (jnp default) is fine here: out-of-range rows gather garbage
    # that the caller's length mask removes before the softmax
    gathered = cache[jnp.clip(block_rows, 0, cache.shape[0] - 1)]
    return gathered.reshape(b, m * bs, kh, hd)


def paged_write_step(cache: jax.Array, block_rows: jax.Array,
                     positions: jax.Array, new: jax.Array,
                     active: jax.Array) -> jax.Array:
    """Scatter one token's K (or V) per batch slot into the pool.

    cache: [N, Bs, KH, hd]; block_rows: [B, M]; positions: [B] (the
    sequence index being written); new: [B, KH, hd]; active: [B] bool.
    Inactive slots are dropped (scattered out of bounds), so a padded
    slot can never clobber a block owned by a live sequence.
    """
    n, bs = cache.shape[0], cache.shape[1]
    b = positions.shape[0]
    m = block_rows.shape[1]
    block_idx = jnp.clip(positions // bs, 0, m - 1)
    bids = block_rows[jnp.arange(b), block_idx]
    bids = jnp.where(active, bids, n)  # out of bounds -> dropped
    return cache.at[bids, positions % bs].set(
        new.astype(cache.dtype), mode="drop")


def paged_write_prefill(cache: jax.Array, block_row: jax.Array,
                        seq: jax.Array, length: jax.Array,
                        start=0) -> jax.Array:
    """Scatter a prompt's K (or V) sequence into one block-table row.

    cache: [N, Bs, KH, hd]; block_row: [M]; seq: [S, KH, hd] (S is the
    static prefill bucket); length: scalar int32 — positions >= length
    are padding and dropped. ``start`` (scalar) offsets every write:
    seq[i] lands at sequence position start + i — the suffix-prefill
    path of the prefix cache, where positions [0, start) are already
    resident in cached blocks named by the same row.
    """
    n, bs = cache.shape[0], cache.shape[1]
    s = seq.shape[0]
    pos = jnp.arange(s) + start
    bids = block_row[jnp.clip(pos // bs, 0, block_row.shape[0] - 1)]
    bids = jnp.where(pos < start + length, bids, n)  # pad -> dropped
    return cache.at[bids, pos % bs].set(seq.astype(cache.dtype),
                                        mode="drop")


def paged_attention_prefill(q: jax.Array, k_cache: jax.Array,
                            v_cache: jax.Array, block_row: jax.Array,
                            start: jax.Array,
                            length: jax.Array) -> jax.Array:
    """Causal attention of a suffix over its full paged context.

    The suffix-prefill primitive of the prefix cache: query token i sits
    at sequence position start + i and attends every cached position
    <= its own — the reused prefix ([0, start), written by an earlier
    request) AND the suffix's K/V (written into the same row by
    :func:`paged_write_prefill` with the same ``start`` before this
    call).

    q: [S, H, hd] (S is the static suffix bucket); k_cache/v_cache:
    [N, Bs, KH, hd]; block_row: [M]; start, length: scalars. This op
    does NOT mask pad queries — rows at index >= ``length`` attend
    stale context and are GARBAGE; callers must read only rows below
    ``length`` (the models read exactly the ``length - 1`` row for the
    next-token logits). ``length`` is accepted so the signature mirrors
    :func:`paged_write_prefill` and a masking variant can slot in
    without touching call sites. GQA (KH < H) broadcasts KV heads.
    Returns [S, H, hd] in q's dtype; math in f32.
    """
    del length  # contract documented above; rows >= length are garbage
    s, h, hd = q.shape
    kh = k_cache.shape[2]
    k = paged_gather_kv(k_cache, block_row[None])[0]  # [M*Bs, KH, hd]
    v = paged_gather_kv(v_cache, block_row[None])[0]
    if kh != h:
        rep = h // kh
        k = jnp.repeat(k, rep, axis=1)
        v = jnp.repeat(v, rep, axis=1)
    ctx = k.shape[0]
    scores = jnp.einsum("shd,chd->shc", q.astype(jnp.float32),
                        k.astype(jnp.float32)) / math.sqrt(hd)
    q_pos = start + jnp.arange(s)                             # [S]
    mask = jnp.arange(ctx)[None, :] <= q_pos[:, None]         # causal
    scores = jnp.where(mask[:, None, :], scores, _NEG_INF)    # [S, H, C]
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("shc,chd->shd", probs, v.astype(jnp.float32))
    return out.astype(q.dtype)


def paged_attention_decode(q: jax.Array, k_cache: jax.Array,
                           v_cache: jax.Array, block_rows: jax.Array,
                           lengths: jax.Array) -> jax.Array:
    """One-token-per-slot attention over the paged context.

    q: [B, H, hd]; k_cache/v_cache: [N, Bs, KH, hd]; block_rows: [B, M];
    lengths: [B] — number of valid context positions (INCLUDING the
    token just written this step). GQA (KH < H) broadcasts KV heads.
    Returns [B, H, hd] in q's dtype; math in f32.
    """
    b, h, hd = q.shape
    kh = k_cache.shape[2]
    k = paged_gather_kv(k_cache, block_rows)  # [B, S, KH, hd]
    v = paged_gather_kv(v_cache, block_rows)
    if kh != h:
        rep = h // kh
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    s = k.shape[1]
    scores = jnp.einsum("bhd,bshd->bhs", q.astype(jnp.float32),
                        k.astype(jnp.float32)) / math.sqrt(hd)
    mask = jnp.arange(s)[None, :] < lengths[:, None]          # [B, S]
    scores = jnp.where(mask[:, None, :], scores, _NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhs,bshd->bhd", probs, v.astype(jnp.float32))
    return out.astype(q.dtype)
