"""Fused numeric layers.

Shaped so XLA fuses them into adjacent matmuls (elementwise chains ride the
epilogue/prologue of MXU ops — no hand kernels needed for these; Pallas is
reserved for attention where fusion can't happen automatically). All stats
in f32 even under bf16 params — the TPU mixed-precision recipe.
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def rmsnorm(x: jax.Array, weight: jax.Array, eps: float = 1e-6) -> jax.Array:
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    y = xf * jax.lax.rsqrt(var + eps)
    return (y * weight.astype(jnp.float32)).astype(dtype)


def layernorm(x: jax.Array, weight: jax.Array, bias: jax.Array,
              eps: float = 1e-5) -> jax.Array:
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    y = (xf - mu) * jax.lax.rsqrt(var + eps)
    return (y * weight.astype(jnp.float32) + bias.astype(jnp.float32)).astype(dtype)


def gelu(x: jax.Array) -> jax.Array:
    return jax.nn.gelu(x, approximate=True)


def rope_cache(seq_len: int, head_dim: int,
               base: float = 10000.0) -> Tuple[jax.Array, jax.Array]:
    """Precompute rotary cos/sin tables: [seq_len, head_dim/2] each (f32)."""
    half = head_dim // 2
    freqs = 1.0 / (base ** (jnp.arange(0, half, dtype=jnp.float32) / half))
    t = jnp.arange(seq_len, dtype=jnp.float32)
    angles = jnp.outer(t, freqs)
    return jnp.cos(angles), jnp.sin(angles)


def apply_rope(x: jax.Array, cos: jax.Array, sin: jax.Array,
               positions: Optional[jax.Array] = None) -> jax.Array:
    """Rotary embedding. x: [B, S, H, D]; cos/sin: [S_max, D/2];
    positions: [B, S] overrides the default arange (decode steps)."""
    dtype = x.dtype
    if positions is not None:
        c = cos[positions]          # [B, S, D/2]
        s = sin[positions]
    else:
        c = cos[None, : x.shape[1]]  # [1, S, D/2]
        s = sin[None, : x.shape[1]]
    c = c[:, :, None, :]            # [B|1, S, 1, D/2]
    s = s[:, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    rot = jnp.concatenate([x1 * c - x2 * s, x1 * s + x2 * c], axis=-1)
    return rot.astype(dtype)


def cross_entropy_loss(logits: jax.Array, labels: jax.Array,
                       ignore_index: int = -100,
                       z_loss: float = 0.0) -> jax.Array:
    """Token-mean cross entropy with optional z-loss (logit drift control,
    the PaLM trick). logits [..., V] f32-upcast; labels [...] int."""
    lf = logits.astype(jnp.float32)
    lse = jax.scipy.special.logsumexp(lf, axis=-1)
    gold = jnp.take_along_axis(
        lf, jnp.maximum(labels, 0)[..., None], axis=-1)[..., 0]
    nll = lse - gold
    if z_loss:
        nll = nll + z_loss * jnp.square(lse)
    mask = (labels != ignore_index).astype(jnp.float32)
    return jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1.0)
