"""ray_tpu.ops — TPU kernels (Pallas) and fused numerics.

The compute-hot path of the framework. The reference has no first-party
kernels (its CUDA appears only through torch/NCCL deps — SURVEY.md §2
legend); for a TPU-native framework the hot ops are first-party:

- flash_attention: tiled online-softmax attention on the MXU (Pallas).
- ring_attention: context-parallel attention over the `sp` mesh axis —
  K/V blocks rotate the ring via ppermute while compute overlaps.
- fused layers: rmsnorm/layernorm/rope/cross-entropy shaped so XLA fuses
  them into adjacent matmuls.

Everything here runs in Pallas interpret mode on CPU (tests) and compiled
on TPU.
"""
from .attention import mha_reference
from .flash_attention import flash_attention
from .ring_attention import ring_attention
from .layers import (cross_entropy_loss, gelu, layernorm, rmsnorm,
                     rope_cache, apply_rope)
from .paged_attention import (paged_attention_decode,
                              paged_attention_prefill, paged_gather_kv,
                              paged_write_prefill, paged_write_step)

__all__ = [
    "flash_attention", "ring_attention", "mha_reference",
    "rmsnorm", "layernorm", "gelu", "rope_cache", "apply_rope",
    "cross_entropy_loss",
    "paged_attention_decode", "paged_attention_prefill",
    "paged_gather_kv", "paged_write_prefill",
    "paged_write_step",
]
