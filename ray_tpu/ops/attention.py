"""Reference (non-Pallas) attention — the correctness oracle.

Used by tests to validate the Pallas kernels and as the fallback path on
platforms without Mosaic. Pure jnp; XLA still fuses this well enough for
small models.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def mha_reference(q: jax.Array, k: jax.Array, v: jax.Array,
                  causal: bool = True,
                  sm_scale: Optional[float] = None,
                  bias: Optional[jax.Array] = None) -> jax.Array:
    """Multi-head attention. Shapes: q [B, Sq, H, D], k/v [B, Skv, H, D]
    (supports Sq != Skv for ring-attention blocks). Returns [B, Sq, H, D].
    Computed in f32 regardless of input dtype (matches the kernel)."""
    orig_dtype = q.dtype
    if sm_scale is None:
        sm_scale = 1.0 / (q.shape[-1] ** 0.5)
    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    logits = jnp.einsum("bqhd,bkhd->bhqk", qf, kf) * sm_scale
    if bias is not None:
        logits = logits + bias
    if causal:
        sq, sk = q.shape[1], k.shape[1]
        qi = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
        ki = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
        # offset aligns the diagonals when Sq != Skv (final-block semantics)
        mask = qi + (sk - sq) >= ki
        logits = jnp.where(mask[None, None], logits, -jnp.inf)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs, vf)
    return out.astype(orig_dtype)
