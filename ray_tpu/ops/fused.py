"""Fused block-entry / block-exit Pallas kernels for the transformer
block — the round-3-plan item whose A/B number the round-4 verdict asked
for (docs/PERF_NOTES.md round-5 MFU section for the measured result).

`ln_matmul`     : layernorm(x) @ w + b in one kernel — the LN read/write
                  of the [N, D] activation never round-trips HBM.
`matmul_residual`: a @ w + b + residual in one kernel — the residual add
                  fuses into the projection's output store.

Both are forward-only Pallas with a custom_vjp whose backward is the
plain XLA composition (recompute-from-inputs), so training A/B runs
measure the forward fusion inside an otherwise identical step. bf16
inputs feed the MXU (preferred_element_type=f32); LN statistics are f32
on the VPU (pallas_guide.md recipe).

Reference capability (not design): the reference leaves this fusion to
torch.compile/Inductor; on TPU it is XLA's job, and these kernels exist
to measure whether hand-fusion beats XLA's — see PERF_NOTES for the
answer.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _use_interpret() -> bool:
    return jax.default_backend() != "tpu"


def _ln_matmul_kernel(x_ref, g_ref, b_ref, w_ref, wb_ref, o_ref, *,
                      eps: float):
    x = x_ref[...].astype(jnp.float32)              # [bm, D]
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x - mean), axis=-1, keepdims=True)
    h = (x - mean) * jax.lax.rsqrt(var + eps)
    h = h * g_ref[0, :].astype(jnp.float32) \
        + b_ref[0, :].astype(jnp.float32)
    h = h.astype(w_ref.dtype)
    acc = jax.lax.dot_general(h, w_ref[...], (((1,), (0,)), ((), ())),
                              preferred_element_type=jnp.float32)
    o_ref[...] = (acc + wb_ref[0, :].astype(jnp.float32)).astype(o_ref.dtype)


def _ln_matmul_fwd_impl(x, g, b, w, wb, *, eps: float, block_m: int,
                        block_n: int):
    N, D = x.shape
    _, F = w.shape
    bm = min(block_m, N)
    while N % bm:
        bm //= 2
    bn = min(block_n, F)
    while F % bn:
        bn //= 2
    grid = (N // bm, F // bn)
    # 1-D params ride as [1, D]/[1, F]: Mosaic tiles 1-D operands in
    # lane-sized chunks that partial 1-D blocks can't satisfy
    return pl.pallas_call(
        functools.partial(_ln_matmul_kernel, eps=eps),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, D), lambda i, j: (i, 0)),
            pl.BlockSpec((1, D), lambda i, j: (0, 0)),
            pl.BlockSpec((1, D), lambda i, j: (0, 0)),
            pl.BlockSpec((D, bn), lambda i, j: (0, j)),
            pl.BlockSpec((1, bn), lambda i, j: (0, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((N, F), x.dtype),
        interpret=_use_interpret(),
    )(x, g.reshape(1, D), b.reshape(1, D), w, wb.reshape(1, F))


def _ln_ref(x, g, b, eps):
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(xf - mean), axis=-1, keepdims=True)
    return ((xf - mean) * jax.lax.rsqrt(var + eps) * g.astype(jnp.float32)
            + b.astype(jnp.float32))


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7))
def ln_matmul(x, g, b, w, wb, eps: float = 1e-5, block_m: int = 256,
              block_n: int = 768):
    """layernorm(x, g, b) @ w + wb, fused. x [N,D], w [D,F] -> [N,F]."""
    return _ln_matmul_fwd_impl(x, g, b, w, wb, eps=eps, block_m=block_m,
                               block_n=block_n)


def _ln_matmul_fwd(x, g, b, w, wb, eps, block_m, block_n):
    out = _ln_matmul_fwd_impl(x, g, b, w, wb, eps=eps, block_m=block_m,
                              block_n=block_n)
    return out, (x, g, b, w)


def _ln_matmul_bwd(eps, block_m, block_n, saved, dout):
    x, g, b, w = saved
    # plain XLA backward via recompute — measures only the fwd fusion

    def f(x, g, b, w, wb):
        h = _ln_ref(x, g, b, eps).astype(w.dtype)
        return (h @ w).astype(jnp.float32) + wb.astype(jnp.float32)

    wb0 = jnp.zeros((w.shape[1],), x.dtype)
    _, vjp = jax.vjp(f, x, g, b, w, wb0)
    dx, dg, db, dw, dwb = vjp(dout.astype(jnp.float32))
    return (dx.astype(x.dtype), dg.astype(g.dtype), db.astype(b.dtype),
            dw.astype(w.dtype), dwb.astype(x.dtype))


ln_matmul.defvjp(_ln_matmul_fwd, _ln_matmul_bwd)


def _mm_res_kernel(a_ref, w_ref, b_ref, r_ref, o_ref):
    acc = jax.lax.dot_general(a_ref[...], w_ref[...],
                              (((1,), (0,)), ((), ())),
                              preferred_element_type=jnp.float32)
    acc = acc + b_ref[0, :].astype(jnp.float32) \
        + r_ref[...].astype(jnp.float32)
    o_ref[...] = acc.astype(o_ref.dtype)


def _mm_res_impl(a, w, b, res, *, block_m: int, block_n: int):
    N, D = a.shape
    _, F = w.shape
    bm = min(block_m, N)
    while N % bm:
        bm //= 2
    bn = min(block_n, F)
    while F % bn:
        bn //= 2
    grid = (N // bm, F // bn)
    return pl.pallas_call(
        _mm_res_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, D), lambda i, j: (i, 0)),
            pl.BlockSpec((D, bn), lambda i, j: (0, j)),
            pl.BlockSpec((1, bn), lambda i, j: (0, j)),
            pl.BlockSpec((bm, bn), lambda i, j: (i, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((N, F), a.dtype),
        interpret=_use_interpret(),
    )(a, w, b.reshape(1, F), res)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def matmul_residual(a, w, b, res, block_m: int = 256, block_n: int = 768):
    """a @ w + b + res, fused. a [N,D], w [D,F], res [N,F] -> [N,F]."""
    return _mm_res_impl(a, w, b, res, block_m=block_m, block_n=block_n)


def _mm_res_fwd(a, w, b, res, block_m, block_n):
    return _mm_res_impl(a, w, b, res, block_m=block_m,
                        block_n=block_n), (a, w)


def _mm_res_bwd(block_m, block_n, saved, dout):
    a, w = saved
    d32 = dout.astype(jnp.float32)
    da = (d32 @ w.astype(jnp.float32).T).astype(a.dtype)
    dw = (a.astype(jnp.float32).T @ d32).astype(w.dtype)
    db = jnp.sum(d32, axis=0).astype(a.dtype)
    return da, dw, db, dout


matmul_residual.defvjp(_mm_res_fwd, _mm_res_bwd)
