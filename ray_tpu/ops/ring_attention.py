"""Ring attention — context/sequence parallelism over the `sp` mesh axis.

The reference has NO long-context machinery (SURVEY.md §5 "Long-context /
sequence parallelism: absent") — this is first-class new work for the TPU
build. Sequence is sharded over `sp`; each device keeps its Q shard
resident and K/V shards rotate around the ring via `ppermute` (lowered to
ICI neighbor exchanges by XLA), overlapping transfer with the block
attention compute. Online-softmax partials (out, logsumexp) merge across
steps, so the result is exact attention over the full sequence with
per-device memory O(S/n · S/n).

Call inside shard_map/pjit with q/k/v sharded as [B, S/sp, H, D] on the
`sp` axis. Differentiable (ppermute transposes to ppermute; XLA re-plans
the reverse ring).
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

_NEG_INF = -1e30


def _block_attn_lse(q, k, v, sm_scale: float, causal: bool):
    """Attention over one (q_shard, kv_shard) pair returning normalized out
    and per-row logsumexp. f32 stats. Shapes [B,S,H,D] -> ([B,S,H,D],
    [B,H,S])."""
    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    s = jnp.einsum("bqhd,bkhd->bhqk", qf, kf) * sm_scale
    if causal:
        sq, sk = q.shape[1], k.shape[1]
        rows = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
        cols = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
        s = jnp.where((rows + (sk - sq) >= cols)[None, None], s, _NEG_INF)
    m = jnp.max(s, axis=-1)                                   # [B,H,Q]
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)                                   # [B,H,Q]
    out = jnp.einsum("bhqk,bkhd->bqhd", p, vf)
    lse = m + jnp.log(jnp.maximum(l, 1e-30))
    out = out / jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
    return out, lse


def _merge(o1, lse1, o2, lse2):
    """Numerically-stable merge of two normalized attention partials."""
    m = jnp.maximum(lse1, lse2)
    w1 = jnp.exp(lse1 - m)                                    # [B,H,Q]
    w2 = jnp.exp(lse2 - m)
    tot = jnp.maximum(w1 + w2, 1e-30)
    # [B,H,Q] -> [B,Q,H,1] broadcast against [B,Q,H,D]
    def bc(w):
        return w.transpose(0, 2, 1)[..., None]
    o = (o1 * bc(w1) + o2 * bc(w2)) / bc(tot)
    lse = m + jnp.log(tot)
    return o, lse


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   axis_name: str = "sp",
                   causal: bool = True,
                   sm_scale: Optional[float] = None) -> jax.Array:
    """Exact attention with sequence sharded on `axis_name`.

    q/k/v: local shards [B, S_local, H, D]. Must be invoked inside a
    shard_map/pjit body where `axis_name` is bound.
    """
    if sm_scale is None:
        sm_scale = 1.0 / (q.shape[-1] ** 0.5)
    n = jax.lax.psum(1, axis_name)  # static for a named mesh axis
    my = jax.lax.axis_index(axis_name)

    # Step 0: the diagonal block (our own K/V) — causal within the shard.
    out, lse = _block_attn_lse(q, k, v, sm_scale, causal=causal)
    perm = [(i, (i + 1) % n) for i in range(n)]
    dtype = q.dtype
    for r in range(1, n):
        k = jax.lax.ppermute(k, axis_name, perm)
        v = jax.lax.ppermute(v, axis_name, perm)
        # After r rotations we hold the K/V shard of device (my - r) mod n.
        o_r, lse_r = _block_attn_lse(q, k, v, sm_scale, causal=False)
        if causal:
            # Wrapped shards ((my - r) < 0) are in our future: masked out by
            # sending their weight to zero in the merge.
            valid = (my >= r)
            lse_r = jnp.where(valid, lse_r, _NEG_INF)
        out, lse = _merge(out, lse, o_r, lse_r)
    return out.astype(dtype)


def ring_attention_sharded(q, k, v, mesh, axis_name: str = "sp",
                           causal: bool = True,
                           sm_scale: Optional[float] = None):
    """Convenience wrapper: shard_map ring_attention over `mesh` with
    sequence on `axis_name`, batch on dp/fsdp, heads on tp."""
    from ..jax_compat import shard_map
    from jax.sharding import PartitionSpec as P

    spec = P(("dp", "fsdp"), axis_name, "tp", None)
    fn = shard_map(
        functools.partial(ring_attention, axis_name=axis_name, causal=causal,
                          sm_scale=sm_scale),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    return fn(q, k, v)
