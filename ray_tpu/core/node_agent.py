"""Node agent — joins a head process over TCP and hosts workers + a store.

The remote half of RemoteNode (see remote_node.py). Equivalent of running
the reference's raylet on a joining machine (`ray start --address=...`,
ref: python/ray/scripts/scripts.py:71; python/ray/_private/node.py:1220
start_ray_processes). The agent owns: worker subprocesses (reached over a
local AF_UNIX socket exactly like the in-process Node's), the node's
shared-memory PlasmaStore, and the object-chunk server. All scheduling
stays on the head; the agent executes worker lifecycle commands and relays
workers' core-API calls up the TCP channel.

Object locality: a worker `get` of a non-local object pulls it from the
head in 5 MiB chunks into the LOCAL store first (creating a tracked copy,
ref: object_manager.h:117), then hands the worker a zero-copy local
/dev/shm segment.

Run: python -m ray_tpu.core.node_agent --address HOST:PORT [--num-cpus N]
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import threading
import time
from typing import Dict, Optional

from .config import Config
from .ids import NodeId, ObjectId, WorkerId
from .object_store import (PlasmaStore, SegmentReader, pull_chunks,
                           read_store_chunk)
from .rpc import RpcChannel, RpcServer, connect

_AUTHKEY = b"ray_tpu"


class NodeAgent:
    def __init__(self, head_address, resources: Dict[str, float],
                 labels: Optional[Dict[str, str]] = None,
                 session_dir: Optional[str] = None,
                 node_id: Optional[NodeId] = None):
        self.config = Config()
        self.node_id = node_id or NodeId.from_random()
        self.session_dir = session_dir or os.path.join(
            "/tmp/ray_tpu", f"agent_{self.node_id.hex()[:8]}_{os.getpid()}")
        os.makedirs(self.session_dir, exist_ok=True)
        self.store = PlasmaStore(
            self.node_id,
            capacity_bytes=int(resources.pop("object_store_memory",
                                             self.config.object_store_memory)),
            spill_dir=os.path.join(self.config.object_spilling_dir,
                                   self.node_id.hex()[:8]),
            min_spilling_size=int(self.config.min_spilling_size),
        )
        self.reader = SegmentReader()
        self._lock = threading.RLock()
        self._procs: Dict[WorkerId, subprocess.Popen] = {}
        self._channels: Dict[WorkerId, RpcChannel] = {}
        self._stopped = threading.Event()
        self._sock_path = os.path.join(
            self.session_dir, f"agent_{self.node_id.hex()[:12]}.sock")
        self._server = RpcServer(self._sock_path, self._make_worker_handler,
                                 family="AF_UNIX", authkey=_AUTHKEY)
        # one duplex channel to the head: requests out, commands in
        conn_addr = (tuple(head_address) if isinstance(head_address, list)
                     else head_address)
        self.head = connect(conn_addr, authkey=_AUTHKEY, name="agent",
                            handler=self._handle_head_command,
                            num_handler_threads=8)
        self.head.on_close(self._on_head_lost)
        reply = self.head.call("register_node", {
            "node_id": self.node_id,
            "resources": dict(resources),
            "labels": dict(labels or {}),
            "pid": os.getpid(),
        }, timeout=30)
        head_period = (reply or {}).get(
            "health_check_period_s", self.config.health_check_period_s)
        # periodic liveness signal; a hung/partitioned agent (channel still
        # open, nothing flowing) is declared dead by the head's health
        # monitor when these stop (ref: gcs_health_check_manager.h:39)
        threading.Thread(target=self._heartbeat_loop, args=(head_period,),
                         daemon=True, name="agent-heartbeat").start()

    def _heartbeat_loop(self, period_s: float) -> None:
        period = max(0.05, float(period_s) / 2)
        while not self._stopped.is_set() and not self.head.closed:
            try:
                self.head.notify("heartbeat", None)
            except Exception:
                break  # channel closed mid-send; head loss handler runs
            self._stopped.wait(period)

    # ---- commands from the head ---------------------------------------------

    def _handle_head_command(self, method: str, payload):
        if method == "start_worker":
            self._start_worker(payload["worker_id"])
            return True
        if method == "push_task":
            ch = self._channels.get(payload["worker_id"])
            if ch is None or ch.closed:
                self.head.notify("worker_exit",
                                 {"worker_id": payload["worker_id"]})
                return False
            ch.notify("push_task", payload["spec"])
            return True
        if method == "kill_worker":
            self._kill_worker(payload["worker_id"], payload.get("force", True))
            return True
        if method == "store_delete":
            self.store.delete(payload["object_id"])
            return True
        if method == "store_stats":
            return self.store.stats()
        if method == "object_info":
            seg = self.store.get_segment(payload["object_id"])
            return None if seg is None else seg[1]
        if method == "read_chunk":
            return self._read_chunk(payload["object_id"], payload["offset"],
                                    payload["length"])
        if method == "shutdown":
            threading.Thread(target=self.shutdown,
                             kwargs={"kill": payload.get("kill", False)},
                             daemon=True).start()
            return True
        raise ValueError(f"unknown head command {method}")

    def _read_chunk(self, oid: ObjectId, offset: int, length: int):
        return read_store_chunk(self.store, self.reader, oid, offset, length)

    # ---- worker lifecycle ----------------------------------------------------

    def _start_worker(self, worker_id: WorkerId) -> None:
        env = dict(os.environ)
        env["PYTHONPATH"] = os.pathsep.join(p for p in sys.path if p)
        cmd = [
            sys.executable, "-S", "-m", "ray_tpu.core.worker_main",
            "--address", self._sock_path,
            "--authkey", _AUTHKEY.hex(),
            "--worker-id", worker_id.hex(),
            "--node-id", self.node_id.hex(),
        ]
        proc = subprocess.Popen(cmd, env=env)
        with self._lock:
            self._procs[worker_id] = proc
        threading.Thread(target=self._reap, args=(worker_id, proc),
                         daemon=True).start()

    def _reap(self, worker_id: WorkerId, proc: subprocess.Popen) -> None:
        try:
            proc.wait()
        except Exception:
            return
        with self._lock:
            self._procs.pop(worker_id, None)
            self._channels.pop(worker_id, None)
        if not self._stopped.is_set() and not self.head.closed:
            self.head.notify("worker_exit", {"worker_id": worker_id})

    def _kill_worker(self, worker_id: WorkerId, force: bool) -> None:
        with self._lock:
            proc = self._procs.get(worker_id)
            ch = self._channels.get(worker_id)
        if not force and ch is not None:
            ch.notify("shutdown")
            ch.close()
        if proc is not None:
            try:
                (proc.kill if force else proc.terminate)()
            except Exception:
                pass

    # ---- worker-facing handler (relay) --------------------------------------

    def _make_worker_handler(self, channel: RpcChannel):
        state = {"worker_id": None}

        def handler(method: str, payload):
            if method == "register":
                wid: WorkerId = payload["worker_id"]
                state["worker_id"] = wid
                with self._lock:
                    self._channels[wid] = channel
                channel.on_close(lambda: self._on_worker_channel_close(wid))
                self.head.call("worker_register",
                               {"worker_id": wid,
                                "pid": payload.get("pid", 0)}, timeout=30)
                return True
            wid = state["worker_id"]
            if method == "create_object":
                return self.store.create(payload["object_id"], payload["size"])
            if method == "seal_object":
                self.store.seal(payload["object_id"])
                self.store.pin(payload["object_id"])
                self.head.notify("object_sealed", {
                    "object_id": payload["object_id"],
                    "worker_id": wid,
                    "is_put": bool(payload.get("is_put")),
                })
                return True
            if method == "task_done":
                self.head.notify("task_done", {"worker_id": wid,
                                               "payload": payload})
                return None
            if method == "get_objects":
                return self._get_objects(payload["ids"],
                                         payload.get("timeout"))
            if method == "log_event":
                self.head.notify("worker_call", {"worker_id": wid,
                                                 "method": method,
                                                 "payload": payload})
                return None
            # everything else: relay to the head's core-worker API
            from .rpc import ChannelClosed

            try:
                return self.head.call("worker_call", {"worker_id": wid,
                                                      "method": method,
                                                      "payload": payload})
            except ChannelClosed:
                if self._stopped.is_set() or self.head.closed:
                    return None  # agent shutting down; drop the relay
                raise

        return handler

    def _on_worker_channel_close(self, worker_id: WorkerId) -> None:
        with self._lock:
            self._channels.pop(worker_id, None)
        if not self._stopped.is_set() and not self.head.closed:
            self.head.notify("worker_exit", {"worker_id": worker_id})

    # ---- object pulls --------------------------------------------------------

    def _get_objects(self, ids, timeout):
        out = []
        for oid in ids:
            seg = self.store.get_segment(oid)
            if seg is not None:
                out.append(("shm", seg[0], seg[1]))
                continue
            res = self.head.call("fetch_for_agent",
                                 {"object_id": oid, "timeout": timeout},
                                 timeout=None if timeout is None
                                 else timeout + 30)
            kind = res[0]
            if kind == "inline":
                out.append(res)
                continue
            # ("sized", total): pull chunks from the head into the local
            # store, then serve the local segment zero-copy
            data = pull_chunks(
                lambda off, n: self.head.call(
                    "head_read_chunk",
                    {"object_id": oid, "offset": off, "length": n},
                    timeout=120),
                res[1])
            if data is None:
                raise RuntimeError(
                    f"object {oid.hex()[:12]} vanished mid-transfer")
            self.store.put_bytes(oid, data, pin=True)
            self.head.notify("object_copy", {"object_id": oid})
            seg = self.store.get_segment(oid)
            out.append(("shm", seg[0], seg[1]))
        return out

    # ---- lifecycle -----------------------------------------------------------

    def _on_head_lost(self) -> None:
        if not self._stopped.is_set():
            self.shutdown(kill=True)

    def shutdown(self, kill: bool = False) -> None:
        if self._stopped.is_set():
            return
        self._stopped.set()
        with self._lock:
            procs = dict(self._procs)
            channels = dict(self._channels)
        for ch in channels.values():
            try:
                ch.notify("shutdown")
                ch.close()
            except Exception:
                pass
        for proc in procs.values():
            try:
                (proc.kill if kill else proc.terminate)()
            except Exception:
                pass
        for proc in procs.values():
            try:
                proc.wait(timeout=5)
            except Exception:
                pass
        self._server.close()
        try:
            self.head.close()
        except Exception:
            pass
        self.store.destroy()

    def wait(self) -> None:
        """Block until shut down (the agent main loop)."""
        while not self._stopped.is_set():
            time.sleep(0.2)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description="ray_tpu node agent")
    p.add_argument("--address", required=True,
                   help="head host:port to join")
    p.add_argument("--num-cpus", type=float, default=float(os.cpu_count() or 1))
    p.add_argument("--resources", default="{}",
                   help="extra resources as JSON, e.g. '{\"TPU\": 4}'")
    p.add_argument("--labels", default="{}")
    p.add_argument("--node-id", default="",
                   help="hex node id assigned by the launcher (optional)")
    args = p.parse_args(argv)
    host, _, port = args.address.rpartition(":")
    resources = {"CPU": args.num_cpus, **json.loads(args.resources)}
    agent = NodeAgent((host, int(port)), resources,
                      labels=json.loads(args.labels),
                      node_id=NodeId(bytes.fromhex(args.node_id))
                      if args.node_id else None)
    print(f"ray_tpu node agent {agent.node_id.hex()[:12]} joined "
          f"{args.address}", flush=True)
    try:
        agent.wait()
    except KeyboardInterrupt:
        agent.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
