"""Node agent — joins a head process over TCP and hosts workers + a store.

The remote half of RemoteNode (see remote_node.py). Equivalent of running
the reference's raylet on a joining machine (`ray start --address=...`,
ref: python/ray/scripts/scripts.py:71; python/ray/_private/node.py:1220
start_ray_processes). The agent owns: worker subprocesses (reached over a
local AF_UNIX socket exactly like the in-process Node's), the node's
shared-memory PlasmaStore, and the object-chunk server. All scheduling
stays on the head; the agent executes worker lifecycle commands and relays
workers' core-API calls up the TCP channel.

Object locality: a worker `get` of a non-local object pulls it from the
head in 5 MiB chunks into the LOCAL store first (creating a tracked copy,
ref: object_manager.h:117), then hands the worker a zero-copy local
/dev/shm segment.

Run: python -m ray_tpu.core.node_agent --address HOST:PORT [--num-cpus N]
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import threading
import time
from typing import Dict, Optional

from ..devtools.locks import instrumented_lock
from ..util.retry import RetryPolicy, call_with_retry
from .config import Config
from .ids import NodeId, ObjectId, WorkerId
from .object_store import (make_store, SegmentReader, pull_chunks,
                           read_store_chunk)
from .rpc import RpcChannel, RpcServer, cluster_token, connect


def _outbound_ip_toward(addr) -> str:
    """The local interface address this host would use to reach `addr` —
    the right P2P advertisement when --node-ip isn't given (a UDP connect
    performs routing without sending a packet)."""
    import socket

    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        s.connect((addr[0], int(addr[1]) or 80))
        return s.getsockname()[0]
    except Exception:
        return "127.0.0.1"
    finally:
        s.close()


class NodeAgent:
    def __init__(self, head_address, resources: Dict[str, float],
                 labels: Optional[Dict[str, str]] = None,
                 session_dir: Optional[str] = None,
                 node_id: Optional[NodeId] = None):
        self.config = Config()
        self.node_id = node_id or NodeId.from_random()
        self.session_dir = session_dir or os.path.join(
            "/tmp/ray_tpu", f"agent_{self.node_id.hex()[:8]}_{os.getpid()}")
        os.makedirs(self.session_dir, exist_ok=True)
        self.store = make_store(
            self.node_id,
            capacity_bytes=int(resources.pop("object_store_memory",
                                             self.config.object_store_memory)),
            spill_dir=os.path.join(self.config.object_spilling_dir,
                                   self.node_id.hex()[:8]),
            min_spilling_size=int(self.config.min_spilling_size),
        )
        self.reader = SegmentReader()
        self._lock = instrumented_lock("node_agent", reentrant=True)
        self._procs: Dict[WorkerId, subprocess.Popen] = {}
        self._channels: Dict[WorkerId, RpcChannel] = {}
        # bounded per-worker log ring: the local tail survives head-side
        # eviction / link loss for on-node triage (ref: per-node worker
        # log files in the reference; here in-memory, byte-light)
        from collections import deque as _deque

        self._log_ring_lines = int(self.config.agent_log_ring_lines)
        self._log_rings: Dict[WorkerId, _deque] = {}
        self._stopped = threading.Event()
        self._shutdown_claim = threading.Lock()
        self._drain_deadline = 0.0  # set by the head's "drain" command
        # deterministic fault injection on this agent process too (env is
        # inherited from the launcher): frame-level chaos applies to the
        # agent's head/worker/peer channels
        from .. import chaos as _chaos_mod

        _chaos_mod.maybe_enable_from_env()
        self._sock_path = os.path.join(
            self.session_dir, f"agent_{self.node_id.hex()[:12]}.sock")
        self._server = RpcServer(self._sock_path, self._make_worker_handler,
                                 family="AF_UNIX")
        conn_addr = (tuple(head_address) if isinstance(head_address, list)
                     else head_address)
        # peer-facing object server: other agents pull chunks DIRECTLY from
        # here instead of relaying through the head (ref: object_manager.h:117
        # — raylets push chunks peer-to-peer; head DCN bandwidth must not be
        # the cluster ceiling). Authenticated with the same cluster token.
        # Binds all interfaces; ADVERTISES --node-ip / RTPU_NODE_IP, or the
        # interface this host uses to reach the head (loopback advertisement
        # would silently defeat cross-machine P2P).
        peer_host = (os.environ.get("RTPU_NODE_IP")
                     or _outbound_ip_toward(conn_addr))
        self._peer_server = RpcServer(("0.0.0.0", 0),
                                      self._make_peer_handler,
                                      family="AF_INET",
                                      num_handler_threads=8)
        self._peer_addr = (peer_host, self._peer_server.address[1])
        self._peer_channels: Dict[tuple, RpcChannel] = {}
        # one duplex channel to the head: requests out, commands in.
        # authkey = the cluster token (from --authkey / RTPU_AUTHKEY).
        # Joining retries with backoff (util/retry.py): on pod bring-up
        # the agent routinely starts before the head is listening, and a
        # restarted head should find its agents reconnecting rather than
        # dead (docs/FAULT_TOLERANCE.md).
        self.head = call_with_retry(
            lambda: connect(conn_addr, name="agent",
                            handler=self._handle_head_command,
                            num_handler_threads=8),
            policy=RetryPolicy(initial_backoff_s=0.2, multiplier=2.0,
                               max_backoff_s=2.0, deadline_s=30.0),
            retry_on=(OSError, ConnectionError),
            description=f"agent join {conn_addr}")
        self.head.on_close(self._on_head_lost)
        reply = self.head.call("register_node", {
            "node_id": self.node_id,
            "resources": dict(resources),
            "labels": dict(labels or {}),
            "pid": os.getpid(),
            "object_server_addr": tuple(self._peer_addr),
        }, timeout=30)
        head_period = (reply or {}).get(
            "health_check_period_s", self.config.health_check_period_s)
        # periodic liveness signal; a hung/partitioned agent (channel still
        # open, nothing flowing) is declared dead by the head's health
        # monitor when these stop (ref: gcs_health_check_manager.h:39)
        threading.Thread(target=self._heartbeat_loop, args=(head_period,),
                         daemon=True, name="agent-heartbeat").start()

    def _heartbeat_loop(self, period_s: float) -> None:
        period = max(0.05, float(period_s) / 2)
        backlog: list = []  # deltas snapshotted but not yet shipped
        while not self._stopped.is_set() and not self.head.closed:
            try:
                # piggyback this agent process's metric deltas (store
                # ops, RPC latency) on the liveness signal — the head
                # merges them node-tagged into its /metrics exposition
                from ..util import metrics as metrics_mod

                try:
                    backlog = metrics_mod.carry_backlog(backlog)
                except Exception:
                    pass
                if self.head.closed:
                    break
                self.head.notify("heartbeat", backlog or None)
                backlog = []
            except Exception:
                break  # channel closed mid-send; head loss handler runs
            self._stopped.wait(period)

    # ---- commands from the head ---------------------------------------------

    def _handle_head_command(self, method: str, payload):
        if method == "start_worker":
            self._start_worker(payload["worker_id"],
                               container=payload.get("container"))
            return True
        if method == "push_task":
            ch = self._channels.get(payload["worker_id"])
            if ch is None or ch.closed:
                self.head.notify("worker_exit",
                                 {"worker_id": payload["worker_id"]})
                return False
            ch.notify("push_task", payload["spec"])
            return True
        if method == "kill_worker":
            self._kill_worker(payload["worker_id"], payload.get("force", True))
            return True
        if method == "store_delete":
            self.store.delete(payload["object_id"])
            return True
        if method == "store_stats":
            return self.store.stats()
        if method == "worker_stack":
            # on-demand stack dump relay: head -> this agent -> worker
            # (remote workers have no head-side channel; ref: `ray stack`
            # fans out through each node's agent)
            ch = self._channels.get(payload["worker_id"])
            if ch is None or ch.closed:
                raise RuntimeError("worker is not connected to this agent")
            return ch.call("dump_stacks", None,
                           timeout=float(payload.get("timeout", 5.0)))
        if method == "worker_profile":
            ch = self._channels.get(payload["worker_id"])
            if ch is None or ch.closed:
                raise RuntimeError("worker is not connected to this agent")
            duration = float(payload.get("duration_s", 5.0))
            return ch.call("profile",
                           {"duration_s": duration,
                            "interval_s": payload.get("interval_s", 0.01)},
                           timeout=duration + 30.0)
        if method == "agent_logs":
            # the local per-worker ring (head-store-independent tail)
            wid = payload.get("worker_id")
            with self._lock:
                rings = ([self._log_rings.get(wid)] if wid is not None
                         else list(self._log_rings.values()))
            out = []
            for ring in rings:
                if ring:
                    out.extend(list(ring))
            return out[-int(payload.get("limit", 1000)):]
        if method == "object_info":
            seg = self.store.get_segment(payload["object_id"])
            return None if seg is None else seg[1]
        if method == "read_chunk":
            return self._read_chunk(payload["object_id"], payload["offset"],
                                    payload["length"])
        if method == "store_put_chunk":
            # head -> agent object push (the inverse of read_chunk; lets
            # the head place a driver put on this node's store)
            return self.store.put_chunk(
                payload["object_id"], payload["offset"], payload["total"],
                payload["data"])
        if method == "worker_notify":
            # generic head -> worker oneway relay (compiled-graph envelope
            # delivery and stop fencing ride this)
            ch = self._channels.get(payload["worker_id"])
            if ch is not None and not ch.closed:
                ch.notify(payload["method"], payload["payload"])
            return None
        if method == "worker_relay_call":
            # generic head -> worker request relay (cgraph_load/stop —
            # same shape as the worker_stack introspection relay)
            ch = self._channels.get(payload["worker_id"])
            if ch is None or ch.closed:
                raise RuntimeError("worker is not connected to this agent")
            return ch.call(payload["method"], payload["payload"],
                           timeout=float(payload.get("timeout", 30.0)))
        if method == "cgraph_alloc_channel":
            # compiled-graph channel segment on THIS node's store: both
            # endpoints are workers on this host; the head only needs the
            # shm name for their plans
            return self.store.allocate_channel(payload["cid"],
                                               payload["size"])
        if method == "cgraph_release_channel":
            self.store.release_channel(payload["cid"])
            return True
        if method == "drain":
            # preemption notice relayed by the head (docs/FAULT_TOLERANCE
            # "Elasticity"): the platform kills this host in grace_s. The
            # head already stopped scheduling here; usually the autoscaler
            # terminates us cleanly once the workloads drained. This is
            # the backstop: exit gracefully just BEFORE the axe so the
            # head sees an orderly channel close, never a mid-write kill.
            grace = max(0.0, float(payload.get("grace_s", 0.0)))
            self._drain_deadline = time.monotonic() + grace

            def _drain_backstop():
                wait = max(0.0, grace - max(1.0, 0.1 * grace)) \
                    if grace > 1.5 else grace * 0.9
                if not self._stopped.wait(wait):
                    self.shutdown(kill=False)

            threading.Thread(target=_drain_backstop, daemon=True,
                             name="agent-drain").start()
            return True
        if method == "shutdown":
            threading.Thread(target=self.shutdown,
                             kwargs={"kill": payload.get("kill", False)},
                             daemon=True).start()
            return True
        raise ValueError(f"unknown head command {method}")

    def _read_chunk(self, oid: ObjectId, offset: int, length: int):
        return read_store_chunk(self.store, self.reader, oid, offset, length)

    # ---- peer-to-peer object serving ----------------------------------------

    def _make_peer_handler(self, channel: RpcChannel):
        def handler(method: str, payload):
            if method == "object_info":
                seg = self.store.get_segment(payload["object_id"])
                return None if seg is None else seg[1]
            if method == "read_chunk":
                return self._read_chunk(payload["object_id"],
                                        payload["offset"], payload["length"])
            raise ValueError(f"unknown peer message {method}")

        return handler

    # peer reconnect policy (util/retry.py): an accept-backlog refusal
    # on a busy holder must not immediately push the pull onto the head
    # relay, but a truly dead peer should fail over fast
    _PEER_CONNECT = RetryPolicy(initial_backoff_s=0.05, multiplier=2.0,
                                max_backoff_s=0.4, max_attempts=3)

    def _peer_channel(self, addr: tuple) -> RpcChannel:
        with self._lock:
            ch = self._peer_channels.get(addr)
            if ch is not None and not ch.closed:
                return ch
        ch = call_with_retry(
            lambda: connect(addr, name="peer", num_handler_threads=2),
            policy=self._PEER_CONNECT,
            retry_on=(OSError, ConnectionError),
            description=f"peer connect {addr}")
        with self._lock:
            old = self._peer_channels.get(addr)
            if old is not None and not old.closed:
                ch.close()
                return old
            self._peer_channels[addr] = ch
        return ch

    def _pull_from_peers(self, oid: ObjectId, peers) -> Optional[bytes]:
        """Try each holder's object server in turn; None = no peer could
        serve it (caller falls back to the head relay)."""
        for addr in peers:
            try:
                ch = self._peer_channel(tuple(addr))
                size = ch.call("object_info", {"object_id": oid}, timeout=30)
                if size is None:
                    continue  # holder evicted it since the head looked
                data = pull_chunks(
                    lambda off, n, ch=ch: ch.call(
                        "read_chunk",
                        {"object_id": oid, "offset": off, "length": n},
                        timeout=120),
                    size)
                if data is not None:
                    return data
            except Exception:
                continue  # peer unreachable/dying: next copy or fallback
        return None

    # ---- worker lifecycle ----------------------------------------------------

    def _start_worker(self, worker_id: WorkerId,
                      container: dict | None = None) -> None:
        env = dict(os.environ)
        env["PYTHONPATH"] = os.pathsep.join(p for p in sys.path if p)
        env["RTPU_AUTHKEY"] = cluster_token().hex()  # env, never argv
        cmd = [
            sys.executable, "-S", "-m", "ray_tpu.core.worker_main",
            "--address", self._sock_path,
            "--worker-id", worker_id.hex(),
            "--node-id", self.node_id.hex(),
        ]
        if container:
            # same launcher contract as Node._start_worker, on THIS host
            from .runtime_env import container_command

            cmd = container_command(self.config.container_launcher,
                                    container, cmd)
        try:
            proc = subprocess.Popen(cmd, env=env)
        except OSError as e:
            # launcher missing/unexecutable: report the launch failure so
            # the head releases the 'starting' slot and fails the lease
            # instead of waiting forever for a register
            if not self._stopped.is_set() and not self.head.closed:
                self.head.notify("worker_exit", {
                    "worker_id": worker_id,
                    "error": f"worker launch failed ({cmd[0]}): {e}"})
            return
        with self._lock:
            self._procs[worker_id] = proc
        threading.Thread(target=self._reap, args=(worker_id, proc),
                         daemon=True).start()

    def _reap(self, worker_id: WorkerId, proc: subprocess.Popen) -> None:
        try:
            proc.wait()
        except Exception:
            return
        with self._lock:
            self._procs.pop(worker_id, None)
            self._channels.pop(worker_id, None)
        if not self._stopped.is_set() and not self.head.closed:
            self.head.notify("worker_exit", {"worker_id": worker_id})

    def _kill_worker(self, worker_id: WorkerId, force: bool) -> None:
        with self._lock:
            proc = self._procs.get(worker_id)
            ch = self._channels.get(worker_id)
        if not force and ch is not None:
            ch.notify("shutdown")
            ch.close()
        if proc is not None:
            try:
                (proc.kill if force else proc.terminate)()
            except Exception:
                pass

    # ---- worker-facing handler (relay) --------------------------------------

    def _make_worker_handler(self, channel: RpcChannel):
        state = {"worker_id": None}

        def handler(method: str, payload):
            if method == "register":
                wid: WorkerId = payload["worker_id"]
                state["worker_id"] = wid
                with self._lock:
                    self._channels[wid] = channel
                channel.on_close(lambda: self._on_worker_channel_close(wid))
                self.head.call("worker_register",
                               {"worker_id": wid,
                                "pid": payload.get("pid", 0),
                                "direct_addr": payload.get("direct_addr")},
                               timeout=30)
                # prints from workers on this host can't reach the driver's
                # console — have them tee lines up the channel
                return {"forward_logs": True}
            wid = state["worker_id"]
            if method == "create_object":
                return self.store.create(payload["object_id"], payload["size"])
            if method == "seal_object":
                self.store.seal(payload["object_id"])
                self.store.pin(payload["object_id"])
                self.head.notify("object_sealed", {
                    "object_id": payload["object_id"],
                    "worker_id": wid,
                    "is_put": bool(payload.get("is_put")),
                    "size": self.store.object_size(payload["object_id"]),
                })
                return True
            if method == "task_done":
                self.head.notify("task_done", {"worker_id": wid,
                                               "payload": payload})
                return None
            if method == "get_objects":
                return self._get_objects(payload["ids"],
                                         payload.get("timeout"))
            if method in ("log_event", "worker_log", "metrics_push",
                          "task_events_batch"):
                if method == "worker_log":
                    from collections import deque as _deque

                    with self._lock:
                        ring = self._log_rings.get(wid)
                        if ring is None:
                            # rings outlive their worker (post-mortem
                            # tail) but the table stays bounded: evict
                            # a dead worker's ring past the cap
                            if len(self._log_rings) >= 64:
                                for old in list(self._log_rings):
                                    if old not in self._channels:
                                        self._log_rings.pop(old, None)
                                        break
                            ring = self._log_rings[wid] = _deque(
                                maxlen=self._log_ring_lines)
                        whex = wid.hex() if wid is not None else ""
                        for rec in payload.get("recs", ()):
                            ring.append({"worker_id": whex,
                                         "pid": payload.get("pid"),
                                         "rec": list(rec)})
                self.head.notify("worker_call", {"worker_id": wid,
                                                 "method": method,
                                                 "payload": payload})
                return None
            # everything else: relay to the head's core-worker API
            from .rpc import ChannelClosed

            try:
                return self.head.call("worker_call", {"worker_id": wid,
                                                      "method": method,
                                                      "payload": payload})
            except ChannelClosed:
                if self._stopped.is_set() or self.head.closed:
                    return None  # agent shutting down; drop the relay
                raise

        return handler

    def _on_worker_channel_close(self, worker_id: WorkerId) -> None:
        with self._lock:
            self._channels.pop(worker_id, None)
        if not self._stopped.is_set() and not self.head.closed:
            self.head.notify("worker_exit", {"worker_id": worker_id})

    # ---- object pulls --------------------------------------------------------

    def _get_objects(self, ids, timeout):
        out = []
        for oid in ids:
            seg = self.store.get_segment(oid)
            if seg is not None:
                out.append(("shm", seg[0], seg[1]))
                continue
            res = self.head.call("fetch_for_agent",
                                 {"object_id": oid, "timeout": timeout},
                                 timeout=None if timeout is None
                                 else timeout + 30)
            kind = res[0]
            if kind == "inline":
                out.append(res)
                continue
            data = None
            if kind == "remote":
                # the head answered with LOCATIONS: pull chunks directly
                # from a holding agent (P2P); the head never touches the
                # bytes (ref: object_manager.h:117)
                data = self._pull_from_peers(oid, res[1])
                if data is None:
                    # every peer failed: ask the head to relay (it pulls
                    # the object into its own store and serves chunks)
                    res = self.head.call(
                        "fetch_for_agent",
                        {"object_id": oid, "timeout": timeout,
                         "relay": True},
                        timeout=None if timeout is None else timeout + 30)
                    if res[0] == "inline":
                        out.append(res)
                        continue
            if data is None:
                # ("sized", total): pull chunks from the head's store
                data = pull_chunks(
                    lambda off, n: self.head.call(
                        "head_read_chunk",
                        {"object_id": oid, "offset": off, "length": n},
                        timeout=120),
                    res[1])
            if data is None:
                raise RuntimeError(
                    f"object {oid.hex()[:12]} vanished mid-transfer")
            self.store.put_bytes(oid, data, pin=True)
            self.head.notify("object_copy", {"object_id": oid})
            seg = self.store.get_segment(oid)
            out.append(("shm", seg[0], seg[1]))
        return out

    # ---- lifecycle -----------------------------------------------------------

    def _on_head_lost(self) -> None:
        if not self._stopped.is_set():
            self.shutdown(kill=True)

    def shutdown(self, kill: bool = False) -> None:
        # atomic claim: the head-loss callback, a head "shutdown" command,
        # and SIGINT can all race here — exactly one caller runs the body
        # (Event.is_set()+set() as two steps let two callers both enter)
        with self._shutdown_claim:
            if self._stopped.is_set():
                return
            self._stopped.set()
        with self._lock:
            procs = dict(self._procs)
            channels = dict(self._channels)
            peer_channels = dict(self._peer_channels)
        for ch in peer_channels.values():
            try:
                ch.close()
            except Exception:
                pass
        try:
            self._peer_server.close()
        except Exception:
            pass
        for ch in channels.values():
            try:
                ch.notify("shutdown")
                ch.close()
            except Exception:
                pass
        for proc in procs.values():
            try:
                (proc.kill if kill else proc.terminate)()
            except Exception:
                pass
        for proc in procs.values():
            try:
                proc.wait(timeout=5)
            except Exception:
                pass
        self._server.close()
        try:
            self.head.close()
        except Exception:
            pass
        self.store.destroy()

    def wait(self) -> None:
        """Block until shut down (the agent main loop)."""
        while not self._stopped.is_set():
            time.sleep(0.2)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description="ray_tpu node agent")
    p.add_argument("--address", required=True,
                   help="head host:port to join")
    p.add_argument("--num-cpus", type=float, default=float(os.cpu_count() or 1))
    p.add_argument("--resources", default="{}",
                   help="extra resources as JSON, e.g. '{\"TPU\": 4}'")
    p.add_argument("--labels", default="{}")
    p.add_argument("--node-id", default="",
                   help="hex node id assigned by the launcher (optional)")
    p.add_argument("--authkey", default="",
                   help="cluster auth token (hex) from the head's join "
                        "command; RTPU_AUTHKEY env is the alternative")
    p.add_argument("--node-ip", default="",
                   help="address other agents use to reach this node's "
                        "object server (default: auto-detect the interface "
                        "facing the head)")
    args = p.parse_args(argv)
    if args.authkey:
        os.environ["RTPU_AUTHKEY"] = args.authkey
    if args.node_ip:
        os.environ["RTPU_NODE_IP"] = args.node_ip
    host, _, port = args.address.rpartition(":")
    resources = {"CPU": args.num_cpus, **json.loads(args.resources)}
    agent = NodeAgent((host, int(port)), resources,
                      labels=json.loads(args.labels),
                      node_id=NodeId(bytes.fromhex(args.node_id))
                      if args.node_id else None)
    from ..util.logs import get_logger

    get_logger("ray_tpu.agent").info(
        "node agent %s joined %s", agent.node_id.hex()[:12], args.address)
    try:
        agent.wait()
    except KeyboardInterrupt:
        agent.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
