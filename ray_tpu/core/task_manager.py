"""Task manager + reference counter (owner-side bookkeeping).

Equivalent of the reference's core-worker TaskManager
(ref: src/ray/core_worker/task_manager.h:173 — pending table, retries
:367 RetryTaskIfPossible, lineage-based resubmission :234 ResubmitTask with a
byte budget :180) and ReferenceCounter (reference_count.h:61 —
ownership-based distributed refcounting).

Deviation from the reference: ownership is centralized on the head runtime
(single-controller), so the borrower protocol reduces to per-process refcount
reports aggregated here rather than owner-to-borrower long-poll chains.
"""
from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Set

from ..devtools.locks import instrumented_lock
from .ids import ObjectId, TaskId
from .task_spec import TaskSpec


@dataclass
class PendingTask:
    spec: TaskSpec
    retries_left: int
    reconstructions_left: int = 3
    submitted_at: float = field(default_factory=time.monotonic)
    state: str = "PENDING"  # PENDING | RUNNING | FINISHED | FAILED


class TaskManager:
    def __init__(self, lineage_max_bytes: int = 256 * 1024 * 1024):
        self._lock = instrumented_lock("task_manager", reentrant=True)
        self._pending: Dict[TaskId, PendingTask] = {}
        # lineage: task prefix (first 12 id bytes) -> spec of the task that
        # created those objects; bounded by _lineage_bytes budget
        self._lineage: Dict[bytes, TaskSpec] = {}
        self._lineage_bytes = 0
        self._lineage_max_bytes = lineage_max_bytes
        self._lineage_order: List[bytes] = []

    def register(self, spec: TaskSpec) -> PendingTask:
        with self._lock:
            pt = PendingTask(spec=spec, retries_left=spec.max_retries)
            self._pending[spec.task_id] = pt
            self._record_lineage(spec)
            return pt

    def _record_lineage(self, spec: TaskSpec) -> None:
        prefix = spec.task_id.binary()[:12]
        if prefix in self._lineage:
            return
        approx = 256 + sum(
            len(a[1]) if a[0] == 0 and isinstance(a[1], bytes) else 64
            for a in spec.args)
        self._lineage[prefix] = spec
        self._lineage_order.append(prefix)
        self._lineage_bytes += approx
        while self._lineage_bytes > self._lineage_max_bytes and self._lineage_order:
            old = self._lineage_order.pop(0)
            self._lineage.pop(old, None)
            self._lineage_bytes -= 256  # rough; budget is advisory

    def get(self, task_id: TaskId) -> Optional[PendingTask]:
        with self._lock:
            return self._pending.get(task_id)

    def mark_running(self, task_id: TaskId) -> None:
        with self._lock:
            pt = self._pending.get(task_id)
            if pt:
                pt.state = "RUNNING"

    def complete(self, task_id: TaskId) -> None:
        with self._lock:
            pt = self._pending.pop(task_id, None)
            if pt:
                pt.state = "FINISHED"

    def fail(self, task_id: TaskId) -> None:
        with self._lock:
            pt = self._pending.pop(task_id, None)
            if pt:
                pt.state = "FAILED"

    def try_retry(self, task_id: TaskId) -> Optional[TaskSpec]:
        """Consume one retry; returns the spec to resubmit, or None if
        exhausted. (ref: task_manager.h:367 RetryTaskIfPossible)"""
        with self._lock:
            pt = self._pending.get(task_id)
            if pt is None or pt.retries_left == 0:
                return None
            if pt.retries_left > 0:
                pt.retries_left -= 1
            pt.state = "PENDING"
            return pt.spec

    def lineage_for_object(self, object_id: ObjectId) -> Optional[TaskSpec]:
        with self._lock:
            return self._lineage.get(object_id.task_prefix())

    def num_pending(self) -> int:
        with self._lock:
            return len(self._pending)


class _RefShard:
    """One shard of the reference counter: its own lock + the per-object
    tables for the object ids hashing here."""

    __slots__ = ("lock", "local", "task_pins", "holders", "owned",
                 "dead_holders")

    def __init__(self, index: int):
        self.lock = instrumented_lock(f"refcounter.s{index}")
        self.local: Dict[ObjectId, int] = {}
        self.task_pins: Dict[ObjectId, int] = {}
        self.holders: Dict[ObjectId, Dict[object, int]] = {}
        self.owned: Set[ObjectId] = set()
        # holders whose process has died: a late add_holder_ref (a relayed
        # call racing the exit notification) must not resurrect a count
        # nothing will ever decrement. WorkerIds are never reused, so the
        # set only grows by one entry per worker lifetime (per shard).
        self.dead_holders: Set[object] = set()


class ReferenceCounter:
    """Aggregated reference counts per object, SHARDED by object id.

    Counts: python-local references in the driver, per-HOLDER references
    reported by worker processes (a holder is a WorkerId; all of a dead
    worker's refs are dropped in one sweep — the single-controller
    reduction of the reference's borrower protocol), plus pins from
    pending task arguments. An object is freeable only when all three
    reach zero. (ref: reference_count.h:61)

    Sharding (docs/DISPATCH.md): every operation touches exactly one
    object id, so the tables split into N independent lock+dict shards —
    submit bursts from many clients stop serializing on one refcount
    lock. Only release_holder (a worker died) sweeps all shards."""

    def __init__(self, on_free: Callable[[ObjectId], None],
                 shards: int = 16):
        self._shards = [_RefShard(i) for i in range(max(1, int(shards)))]
        self._n = len(self._shards)
        self._on_free = on_free

    def _shard(self, object_id: ObjectId) -> _RefShard:
        return self._shards[hash(object_id) % self._n]

    @staticmethod
    def _freeable_locked(s: _RefShard, object_id: ObjectId) -> bool:
        return (object_id not in s.local
                and object_id not in s.task_pins
                and object_id not in s.holders
                and object_id in s.owned)

    def add_owned(self, object_id: ObjectId) -> None:
        s = self._shard(object_id)
        with s.lock:
            s.owned.add(object_id)

    def add_local(self, object_id: ObjectId, n: int = 1) -> None:
        s = self._shard(object_id)
        with s.lock:
            s.local[object_id] = s.local.get(object_id, 0) + n

    def remove_local(self, object_id: ObjectId, n: int = 1) -> None:
        s = self._shard(object_id)
        free = False
        with s.lock:
            c = s.local.get(object_id, 0) - n
            if c <= 0:
                s.local.pop(object_id, None)
                free = self._freeable_locked(s, object_id)
            else:
                s.local[object_id] = c
        if free:
            self._on_free(object_id)

    def add_holder_ref(self, object_id: ObjectId, holder, n: int = 1) -> None:
        """A worker process holds (another) reference to the object."""
        s = self._shard(object_id)
        with s.lock:
            if holder in s.dead_holders:
                return
            h = s.holders.setdefault(object_id, {})
            h[holder] = h.get(holder, 0) + n

    def remove_holder_ref(self, object_id: ObjectId, holder,
                          n: int = 1) -> None:
        s = self._shard(object_id)
        free = False
        with s.lock:
            h = s.holders.get(object_id)
            if h is None:
                return
            c = h.get(holder, 0) - n
            if c <= 0:
                h.pop(holder, None)
            else:
                h[holder] = c
            if not h:
                s.holders.pop(object_id, None)
                free = self._freeable_locked(s, object_id)
        if free:
            self._on_free(object_id)

    def release_holder(self, holder) -> None:
        """Drop every reference a (dead) worker held (all shards)."""
        to_free = []
        for s in self._shards:
            with s.lock:
                s.dead_holders.add(holder)
                for oid in list(s.holders):
                    h = s.holders[oid]
                    if holder in h:
                        h.pop(holder, None)
                        if not h:
                            s.holders.pop(oid, None)
                            if self._freeable_locked(s, oid):
                                to_free.append(oid)
        for oid in to_free:
            self._on_free(oid)

    def pin_for_task(self, object_id: ObjectId) -> None:
        s = self._shard(object_id)
        with s.lock:
            s.task_pins[object_id] = s.task_pins.get(object_id, 0) + 1

    def unpin_for_task(self, object_id: ObjectId) -> None:
        s = self._shard(object_id)
        free = False
        with s.lock:
            c = s.task_pins.get(object_id, 0) - 1
            if c <= 0:
                s.task_pins.pop(object_id, None)
                free = self._freeable_locked(s, object_id)
            else:
                s.task_pins[object_id] = c
        if free:
            self._on_free(object_id)

    def forget(self, object_id: ObjectId) -> None:
        """Freed object: drop residual bookkeeping (the owned marker and
        any stale per-holder rows) so long sessions don't accumulate ids."""
        s = self._shard(object_id)
        with s.lock:
            s.owned.discard(object_id)
            s.holders.pop(object_id, None)
            s.local.pop(object_id, None)
            s.task_pins.pop(object_id, None)

    def counts(self, object_id: ObjectId) -> tuple:
        s = self._shard(object_id)
        with s.lock:
            return (s.local.get(object_id, 0),
                    s.task_pins.get(object_id, 0),
                    sum(s.holders.get(object_id, {}).values()))
