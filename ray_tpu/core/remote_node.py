"""Head-side handle for a node living in another OS process / host.

The reference splits this across the raylet daemon plus the head's
gcs_node_manager and object_manager (ref: src/ray/raylet/node_manager.h:119;
src/ray/object_manager/object_manager.h:117 — chunked pulls;
python/ray/_private/node.py:1183,1220 process bring-up). The TPU-native
reduction keeps the single-controller design: ALL scheduling state (lease
queue, resource ledger, PG bundles) stays on the head in this class, which
reuses Node's logic wholesale; the remote agent process hosts only the
worker subprocesses and the shared-memory store. Control flows over one
duplex TCP channel; bulk object bytes move as chunked reads
(ref: ray_config_def.h:348 — 5 MiB chunks).
"""
from __future__ import annotations

import threading
import time
from typing import Dict, Optional

from .ids import NodeId, ObjectId, WorkerId
from .node import Node, WorkerHandle
from .object_store import SegmentReader, pull_chunks
from .resources import ResourceSet
from .rpc import RpcChannel


class RemoteStoreProxy:
    """The slice of the PlasmaStore interface the head calls on a node.
    Bytes never move through here except via explicit chunk reads."""

    def __init__(self, node: "RemoteNode"):
        self._node = node

    def delete(self, object_id: ObjectId) -> None:
        ch = self._node.channel
        if ch is not None and not ch.closed:
            ch.notify("store_delete", {"object_id": object_id})

    def get_segment(self, object_id: ObjectId):
        # head cannot mmap a remote /dev/shm segment; fetch_one special-
        # cases remote nodes through pull_object_bytes instead
        return None

    def put_serialized(self, object_id, sobj, pin=True):
        """Push a serialized object into the remote agent's store in
        chunks (the inverse of the chunked pull path; ref:
        object_manager.h:117 Push). Unused by the default placement
        policy (driver puts land on the head; remote copies appear via
        execution locality) but fully functional for explicit remote
        placement."""
        data = sobj.to_bytes()
        total = len(data)
        chunk = 5 << 20  # mirror the pull path's 5 MiB chunks
        ch = self._node.channel
        if ch is None or ch.closed:
            raise ConnectionError(
                f"node {self._node.node_id.hex()[:8]} channel closed")
        off = 0
        try:
            while True:
                end = min(off + chunk, total)
                sealed = ch.call("store_put_chunk",
                                 {"object_id": object_id, "offset": off,
                                  "total": total, "data": data[off:end]},
                                 timeout=60)
                off = end
                if off >= total:
                    break
        except Exception:
            # a half-pushed object is an unsealed, unevictable reservation
            # of `total` bytes in the agent's store — release it
            try:
                ch.notify("store_delete", {"object_id": object_id})
            except Exception:
                pass
            raise
        if not sealed:
            raise RuntimeError(
                f"remote put of {object_id.hex()[:12]} did not seal")

    def stats(self) -> dict:
        try:
            return self._node.channel.call("store_stats", None, timeout=10)
        except Exception:
            return {}

    def destroy(self) -> None:
        pass  # owned by the agent process


class RemoteNode(Node):
    """A Node whose workers and store live behind a TCP channel.

    Scheduling (leases, resources, bundles) is inherited from Node and runs
    head-side; worker lifecycle operations are forwarded to the agent.
    """

    is_remote = True

    def __init__(self, runtime, node_id: NodeId, resources: ResourceSet,
                 config, channel: RpcChannel,
                 labels: Optional[Dict[str, str]] = None):
        # deliberately NOT calling Node.__init__ — no local store, no local
        # RpcServer, no prestarted subprocesses. Mirror its ledger state.
        from collections import deque

        from .resources import normalize

        self.runtime = runtime
        self.node_id = node_id
        self.config = config
        self.total_resources = normalize(resources)
        self.available = dict(self.total_resources)
        self.labels = labels or {}
        self.session_dir = runtime.session_dir
        self.store = RemoteStoreProxy(self)
        self.total_resources.pop("object_store_memory", None)
        self.available.pop("object_store_memory", None)
        self._lock = threading.RLock()
        self._workers: Dict[WorkerId, WorkerHandle] = {}
        self._idle = deque()
        self._lease_queue = {}  # (demand, pg, env) sig -> deque (Node's shape)
        self._bundles = {}
        self._starting_count = 0
        self._prefetch_depth = max(1, int(config.worker_task_prefetch))
        self._launch_failures = {}  # Node's launch-strike breaker state
        self.alive = True
        self.draining = False  # preemption-noticed: no NEW work lands here
        self.channel = channel
        self.peer_addr = None  # agent's P2P object-server (host, port)
        self._server = None
        self._reader = SegmentReader()
        self._max_workers = max(int(config.num_workers_soft_limit),
                                int(self.total_resources.get("CPU", 1)))
        channel.on_close(self._on_channel_close)
        # same idle-worker reclamation as the in-process Node: remote
        # workers are terminated over the channel when idle past the limit
        threading.Thread(target=self._idle_reaper_loop, daemon=True,
                         name="idle-reaper").start()

    # ---- worker lifecycle (forwarded) ---------------------------------------

    def _start_worker(self, container=None,
                      env_hash=None) -> WorkerHandle:
        worker_id = WorkerId.from_random()
        handle = WorkerHandle(worker_id=worker_id, proc=None,  # type: ignore
                              started_at=time.monotonic())
        if env_hash is not None:
            handle.env_hash = env_hash  # container workers: dedicated
        with self._lock:  # reentrant: callers may already hold it
            self._workers[worker_id] = handle
            self._starting_count += 1
        msg = {"worker_id": worker_id}
        if container is not None:
            # the agent launches inside the container on ITS host via
            # its configured launcher (same contract as the local Node)
            msg["container"] = dict(container)
        try:
            self.channel.notify("start_worker", msg)
        except Exception:
            self._on_worker_exit(handle)
        return handle

    def on_remote_worker_register(self, worker_id: WorkerId, pid: int,
                                  direct_addr: Optional[str] = None) -> None:
        with self._lock:
            handle = self._workers.get(worker_id)
            if handle is None:
                handle = WorkerHandle(worker_id=worker_id, proc=None,  # type: ignore
                                      pid=pid)
                self._workers[worker_id] = handle
            handle.pid = pid
            handle.direct_addr = direct_addr
            handle.state = "idle"
            self._starting_count = max(0, self._starting_count - 1)
            self._launch_failures.pop(handle.env_hash or "", None)
            self._idle.append(handle)
        self._dispatch()

    def on_remote_worker_exit(self, worker_id: WorkerId,
                              error: str = None) -> None:
        fail_req = None
        with self._lock:
            handle = self._workers.get(worker_id)
            if handle is None:
                return
            launch_failed = handle.state == "starting" and error
            if handle.state == "starting":
                self._starting_count = max(0, self._starting_count - 1)
            if launch_failed:
                # the worker never came up (e.g. container launcher
                # missing on the agent host): fail one queued request of
                # the env this worker was started for, instead of
                # looping start->fail forever
                want_env = handle.env_hash or ""
                for sig in list(self._lease_queue.keys()):
                    if sig[2] == want_env:
                        bucket = self._lease_queue[sig]
                        fail_req = bucket.popleft()
                        if not bucket:
                            del self._lease_queue[sig]
                        break
        if fail_req is not None and not fail_req.future.done():
            from ..exceptions import WorkerCrashedError

            fail_req.future.set_exception(WorkerCrashedError(
                f"remote worker launch failed on node "
                f"{self.node_id.hex()[:8]}: {error}"))
        self._on_worker_exit(handle)

    def _worker_alive(self, w: WorkerHandle) -> bool:
        # no head-side channel object; liveness is tracked by agent exit
        # notifications (the dedication loop lives in Node._pop_idle)
        return True

    def push_task(self, worker: WorkerHandle, spec) -> None:
        from .task_spec import TaskType

        with self._lock:
            worker.in_flight[spec.task_id] = spec
            if spec.task_type == TaskType.ACTOR_CREATION_TASK:
                worker.state = "actor"
                worker.actor_id = spec.actor_id
        if not self.alive or self.channel.closed:
            self._on_worker_exit(worker)
            return
        self.channel.notify("push_task", {"worker_id": worker.worker_id,
                                          "spec": spec})

    def _terminate_worker(self, worker: WorkerHandle) -> None:
        with self._lock:  # the pop must not race a dispatch pass
            worker.state = "dead"
            self._workers.pop(worker.worker_id, None)
        self.runtime.refcount.release_holder(worker.worker_id)
        try:
            self.channel.notify("kill_worker", {"worker_id": worker.worker_id,
                                                "force": False})
        except Exception:
            pass

    def kill_worker(self, worker: WorkerHandle, force: bool = True) -> None:
        try:
            self.channel.notify("kill_worker", {"worker_id": worker.worker_id,
                                                "force": force})
        except Exception:
            pass

    # ---- on-demand introspection (relayed through the agent) -----------------

    def worker_stack(self, worker: WorkerHandle,
                     timeout: float = 5.0) -> dict:
        return self.channel.call(
            "worker_stack", {"worker_id": worker.worker_id,
                             "timeout": float(timeout)},
            timeout=float(timeout) + 10.0)

    def worker_profile(self, worker: WorkerHandle, duration_s: float = 5.0,
                       interval_s: float = 0.01) -> dict:
        return self.channel.call(
            "worker_profile", {"worker_id": worker.worker_id,
                               "duration_s": float(duration_s),
                               "interval_s": float(interval_s)},
            timeout=float(duration_s) + 40.0)

    # ---- compiled-graph control plane (relayed through the agent) ------------

    def worker_notify(self, worker: WorkerHandle, method: str,
                      payload) -> None:
        # raise on a provably-dead channel: the caller (cgraph execute /
        # head routing) must see the envelope as undelivered and run its
        # retraction/abort path rather than strand the consumer on a
        # seq that never arrives
        if not self.alive or self.channel.closed:
            raise RuntimeError(
                f"node {self.node_id.hex()[:8]} channel closed")
        self.channel.notify("worker_notify",
                            {"worker_id": worker.worker_id,
                             "method": method, "payload": payload})

    def worker_cgraph_call(self, worker: WorkerHandle, method: str,
                           payload, timeout: float = 30.0):
        return self.channel.call(
            "worker_relay_call", {"worker_id": worker.worker_id,
                                  "method": method, "payload": payload,
                                  "timeout": float(timeout)},
            timeout=timeout + 10.0)

    # ---- object transfer -----------------------------------------------------

    def pull_object_bytes(self, oid: ObjectId) -> Optional[bytes]:
        """Chunked pull of a remote object's serialized bytes
        (ref: object_manager.h:117 PullManager; 5 MiB chunks).

        Returns None ONLY when the agent definitively reports the object
        absent from its store (copy gone -> caller drops the directory
        entry and lineage recovery can run). Transient RPC failures RAISE
        so the caller retries instead of wrongly declaring the copy lost
        — conflating the two made a get() on an evicted remote copy hang
        forever (advisor r2)."""
        import time as _time

        from .object_store import _observe_op

        t0 = _time.perf_counter()
        size = self.channel.call("object_info", {"object_id": oid},
                                 timeout=30)
        if size is None:
            return None
        data = pull_chunks(
            lambda off, n: self.channel.call(
                "read_chunk",
                {"object_id": oid, "offset": off, "length": n},
                timeout=60),
            size)
        _observe_op("pull", t0, len(data) if data is not None else 0)
        return data

    # ---- lifecycle -----------------------------------------------------------

    def _on_channel_close(self) -> None:
        if not self.alive:
            return
        self.runtime.on_remote_node_lost(self.node_id)

    def shutdown(self, kill: bool = False) -> None:
        from ..exceptions import WorkerCrashedError

        with self._lock:
            if not self.alive:
                return
            self.alive = False
            queued = [r for b in self._lease_queue.values() for r in b]
            self._lease_queue.clear()
        for req in queued:
            if not req.future.done():
                req.future.set_exception(
                    WorkerCrashedError(f"node {self.node_id.hex()[:8]} shut down"))
        try:
            self.channel.notify("shutdown", {"kill": kill})
        except Exception:
            pass
        try:
            self.channel.close()
        except Exception:
            pass
