"""Actor classes, handles, and method invocation.

Equivalent of the reference's actor machinery
(ref: python/ray/actor.py — ActorClass/_remote, ActorHandle with method
wrappers; creation registers with the GCS actor manager
src/ray/gcs/gcs_server/gcs_actor_manager.cc:246; calls go direct to the
actor's worker with client-side sequencing,
src/ray/core_worker/transport/direct_actor_task_submitter.h:67)."""
from __future__ import annotations

import inspect
from typing import Any, Dict, Optional

from . import runtime as runtime_mod
from .config import DEFAULT as cfg
from .ids import ActorId
from .object_ref import ObjectRef
from .remote_function import (prepare_args, resolve_resources, resolve_strategy)
from ..util.tracing import current_context as _trace_ctx
from .task_spec import STREAMING_RETURNS, TaskSpec, TaskType

_VALID_ACTOR_OPTIONS = {
    "num_cpus", "num_tpus", "resources", "max_restarts", "max_task_retries",
    "max_concurrency", "concurrency_groups", "name", "namespace", "lifetime",
    "scheduling_strategy", "memory", "placement_group",
    "placement_group_bundle_index", "runtime_env", "get_if_exists",
}


def _method_meta_of(cls) -> Dict[str, dict]:
    """Per-method defaults set by the @ray_tpu.method decorator."""
    meta: Dict[str, dict] = {}
    for name, m in inspect.getmembers(cls, callable):
        nr = getattr(m, "_rtpu_num_returns", None)
        cg = getattr(m, "_rtpu_concurrency_group", None)
        if nr is not None or cg is not None:
            meta[name] = {"num_returns": nr if nr is not None else 1,
                          "concurrency_group": cg or ""}
    return meta


class ActorMethod:
    def __init__(self, handle: "ActorHandle", name: str,
                 num_returns=1, concurrency_group: str = ""):
        self._handle = handle
        self._name = name
        self._num_returns = num_returns
        self._concurrency_group = concurrency_group

    def options(self, num_returns=None,
                concurrency_group: Optional[str] = None) -> "ActorMethod":
        return ActorMethod(
            self._handle, self._name,
            self._num_returns if num_returns is None else num_returns,
            self._concurrency_group if concurrency_group is None
            else concurrency_group)

    def remote(self, *args, **kwargs):
        return self._handle._invoke(self._name, args, kwargs,
                                    self._num_returns,
                                    self._concurrency_group)

    def bind(self, *args, **kwargs):
        """Declare this method as a node in a static compiled graph
        (ray_tpu.cgraph). Args may be other DAG nodes (dataflow edges)
        or plain values (compile-time constants). Options set via
        ``.options(num_returns=, concurrency_group=)`` carry through
        exactly as they do for ``.remote()``."""
        from ..cgraph.dag import ClassMethodNode

        return ClassMethodNode(self._handle, self._name, args, kwargs,
                               num_returns=self._num_returns,
                               concurrency_group=self._concurrency_group)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Actor method '{self._name}' cannot be called directly; "
            "use .remote() for a dynamic task, or .bind() to build a "
            "compiled graph (ray_tpu.cgraph).")


class ActorHandle:
    def __init__(self, actor_id: ActorId, max_task_retries: int = 0,
                 description: str = "Actor",
                 method_meta: Optional[Dict[str, dict]] = None):
        self._actor_id = actor_id
        self._max_task_retries = max_task_retries
        self._description = description
        self._method_meta = method_meta or {}
        self._ready_ref: Optional[ObjectRef] = None

    def __getattr__(self, name: str) -> ActorMethod:
        if name.startswith("_"):
            raise AttributeError(name)
        meta = self.__dict__.get("_method_meta", {}).get(name, {})
        return ActorMethod(self, name,
                           num_returns=meta.get("num_returns", 1),
                           concurrency_group=meta.get("concurrency_group",
                                                      ""))

    def _invoke(self, method_name: str, args, kwargs, num_returns,
                concurrency_group: str = ""):
        rt = runtime_mod.get_runtime()
        if num_returns == "streaming":
            num_returns = STREAMING_RETURNS
        num_returns = int(num_returns)
        sargs, skwargs = prepare_args(rt, args, kwargs)
        spec = TaskSpec(
            task_id=rt.new_task_id(),
            job_id=getattr(rt, "job_id", None) or _nil_job(),
            task_type=TaskType.ACTOR_TASK,
            func_id="",
            description=f"{self._description}.{method_name}",
            args=sargs,
            kwargs=skwargs,
            num_returns=num_returns,
            resources={},
            max_retries=self._max_task_retries,
            actor_id=self._actor_id,
            method_name=method_name,
            concurrency_group=concurrency_group,
            trace_ctx=_trace_ctx(),
        )
        # wire template: the constant fields of this (actor, method,
        # options) encode once; each call walks only task_id/args/kwargs/
        # seq_no/owner_id/trace_ctx — the actor-call analog of
        # RemoteFunction's template (the submit hot path)
        cache = self.__dict__.setdefault("_tmpl_cache", {})
        key = (method_name, num_returns, concurrency_group)
        tmpl = cache.get(key)
        if tmpl is None:
            from . import wire

            tmpl = cache[key] = wire.make_struct_template(
                spec, ("task_id", "args", "kwargs", "seq_no", "owner_id",
                       "trace_ctx"))
        spec._wire_tmpl = tmpl
        refs = rt.submit_spec(spec)
        if num_returns == STREAMING_RETURNS:
            from .object_ref import ObjectRefGenerator

            return ObjectRefGenerator(spec.task_id, rt)
        if num_returns == 0:
            return None
        if num_returns == 1:
            return refs[0]
        return refs

    def __reduce__(self):
        return (ActorHandle, (self._actor_id, self._max_task_retries,
                              self._description, self._method_meta))

    def __repr__(self):
        return f"ActorHandle({self._description}, {self._actor_id.hex()[:12]})"


class ActorClass:
    def __init__(self, cls, options: Optional[Dict[str, Any]] = None):
        self._cls = cls
        self._options = dict(options or {})
        for k in self._options:
            if k not in _VALID_ACTOR_OPTIONS:
                raise ValueError(f"Invalid actor option {k!r}")
        self._func_ids: Dict[str, str] = {}  # runtime worker_id.hex -> func_id

    def options(self, **overrides) -> "ActorClass":
        merged = dict(self._options)
        merged.update(overrides)
        return ActorClass(self._cls, merged)

    def remote(self, *args, **kwargs) -> ActorHandle:
        rt = runtime_mod.get_runtime()
        opts = self._options
        name = opts.get("name", "")
        if name and opts.get("get_if_exists"):
            existing = _try_get_actor(rt, name, opts.get("namespace"))
            if existing is not None:
                return existing
        rt_key = rt.worker_id.hex()
        func_id = self._func_ids.get(rt_key)
        if func_id is None:
            func_id = rt.export_function(self._cls)
            self._func_ids[rt_key] = func_id
        sargs, skwargs = prepare_args(rt, args, kwargs)
        actor_id = ActorId.from_random()
        is_async = any(
            inspect.iscoroutinefunction(m)
            for _, m in inspect.getmembers(self._cls, inspect.isfunction))
        if is_async and opts.get("concurrency_groups"):
            raise ValueError(
                "concurrency_groups are not supported on async actors yet; "
                "use max_concurrency for asyncio concurrency")
        spec = TaskSpec(
            task_id=rt.new_task_id(),
            job_id=getattr(rt, "job_id", None) or _nil_job(),
            task_type=TaskType.ACTOR_CREATION_TASK,
            func_id=func_id,
            description=f"{self._cls.__name__}.__init__",
            args=sargs,
            kwargs=skwargs,
            num_returns=1,
            resources=resolve_resources(opts, default_cpus=1.0),
            max_retries=0,
            scheduling_strategy=resolve_strategy(opts),
            actor_id=actor_id,
            max_restarts=int(opts.get("max_restarts", cfg.actor_max_restarts)),
            max_concurrency=int(opts.get("max_concurrency", 1)),
            concurrency_groups=opts.get("concurrency_groups"),
            is_async_actor=is_async,
            runtime_env=rt.prepare_runtime_env(opts.get("runtime_env")),
            trace_ctx=_trace_ctx(),
        )
        max_task_retries = int(opts.get("max_task_retries", 0))
        method_meta = _method_meta_of(self._cls)
        meta = {"class_name": self._cls.__name__,
                "max_task_retries": max_task_retries,
                "method_meta": method_meta}
        import time as _time

        deadline = _time.monotonic() + 30.0
        while True:
            try:
                rt.create_actor(spec, name=name,
                                detached=(opts.get("lifetime") == "detached"),
                                meta=meta)
                break
            except Exception as e:
                # get_if_exists creation race: another process registered the
                # name between our lookup and create (the GCS rejects
                # duplicates, ref: gcs_actor_manager.cc name registry). Adopt
                # the winner — or, if the winner died, retry the create (the
                # GCS frees names held by DEAD actors).
                if not (name and opts.get("get_if_exists")
                        and "already taken" in str(e)
                        and _time.monotonic() < deadline):
                    raise
                existing = _try_get_actor(rt, name, opts.get("namespace"))
                if existing is not None:
                    return existing
                _time.sleep(0.01)
        handle = ActorHandle(actor_id, max_task_retries=max_task_retries,
                             description=self._cls.__name__,
                             method_meta=method_meta)
        handle._ready_ref = ObjectRef(spec.return_ids()[0])
        return handle

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Actor class '{self._cls.__name__}' cannot be instantiated "
            "directly; use .remote().")


def _try_get_actor(rt, name: str, namespace: Optional[str]) -> Optional[ActorHandle]:
    try:
        return get_actor(name, namespace)
    except ValueError:
        return None


def get_actor(name: str, namespace: Optional[str] = None) -> ActorHandle:
    rt = runtime_mod.get_runtime()
    ns = namespace or getattr(rt, "namespace", "default")
    if hasattr(rt, "gcs"):  # driver
        info = rt.gcs.get_named_actor(name, ns)
        from .gcs import ActorState

        if info is None or info.state == ActorState.DEAD:
            raise ValueError(f"Failed to look up actor {name!r} in namespace {ns!r}")
        import cloudpickle

        meta_blob = rt.gcs.kv_get("actor_meta:" + info.actor_id.hex(),
                                  namespace="actor")
        meta = cloudpickle.loads(meta_blob) if meta_blob else {}
        return ActorHandle(info.actor_id,
                           max_task_retries=meta.get("max_task_retries", 0),
                           description=meta.get("class_name", "Actor"),
                           method_meta=meta.get("method_meta"))
    res = rt.get_named_actor_info(name, ns)
    if res is None:
        raise ValueError(f"Failed to look up actor {name!r} in namespace {ns!r}")
    import cloudpickle

    meta = cloudpickle.loads(res["meta"]) if res.get("meta") else {}
    return ActorHandle(res["actor_id"],
                       max_task_retries=meta.get("max_task_retries", 0),
                       description=meta.get("class_name", "Actor"),
                       method_meta=meta.get("method_meta"))


def _nil_job():
    from .ids import JobId

    return JobId.nil()
