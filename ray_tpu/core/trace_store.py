"""Head-side request-trace store — tail-sampled span aggregation.

Equivalent of the reference's task-event aggregation for request
timelines (ref: GCS task-event stream feeding the dashboard's request
view), crossed with an OTel tail-sampling collector: workers ship every
span decision-free over the existing delta channel; the head groups
spans by ``trace_id`` and decides at *trace completion* (root span end)
whether to keep it. Always kept: errors, failover hops, preemptions,
and requests slower than the deployment's SLO target (or the global
``trace_slow_threshold_s``). The rest keep with probability
``trace_sample_rate`` under a seedable RNG (deterministic tests).

Storage discipline mirrors ``core/log_store.py``: a byte budget with
oldest-trace eviction, monotonic cursor paging over completed traces,
and condition-variable long-poll follow. Dropped traces leave a
tombstone so late-arriving worker spans are counted
(``ray_tpu_traces_dropped_total{reason="late"}``), not resurrected.
"""
from __future__ import annotations

import random
import threading
from collections import OrderedDict, deque
from typing import Any, Dict, List, Optional

# accounting overhead per span beyond name/attribute text
_SPAN_OVERHEAD = 240
# tombstones remembered for dropped/evicted traces (late-span dedup)
_TOMBSTONES_MAX = 4096

_KEEP_ALWAYS_NAMES = {"serve.failover": "failover", "llm.preempt": "preempt"}


def _span_bytes(span: Dict[str, Any]) -> int:
    n = len(str(span.get("name", "")))
    for k, v in (span.get("attributes") or {}).items():
        n += len(str(k)) + len(str(v))
    return n + _SPAN_OVERHEAD


class TraceStore:
    def __init__(self, max_bytes: Optional[int] = None,
                 sample_rate: Optional[float] = None,
                 slow_threshold_s: Optional[float] = None,
                 seed: Optional[int] = None):
        if max_bytes is None or sample_rate is None \
                or slow_threshold_s is None:
            from .config import DEFAULT as config
            if max_bytes is None:
                max_bytes = config.trace_store_max_bytes
            if sample_rate is None:
                sample_rate = config.trace_sample_rate
            if slow_threshold_s is None:
                slow_threshold_s = config.trace_slow_threshold_s
        self._max_bytes = int(max_bytes)
        self._sample_rate = float(sample_rate)
        self._slow_s = float(slow_threshold_s)
        self._rng = random.Random(seed)
        self._cv = threading.Condition()
        # trace_id -> {spans, bytes, start, end, root, done, keep_reason}
        self._traces: "OrderedDict[str, Dict[str, Any]]" = OrderedDict()
        self._completed: deque = deque()   # kept trace_ids, completion order
        self._base = 0                     # cursor of _completed[0]
        self._bytes = 0
        self._dropped: "OrderedDict[str, None]" = OrderedDict()
        self.total_traces = 0
        self.kept_traces = 0
        self.dropped_sampled = 0
        self.dropped_evicted = 0

    # ---- ingest --------------------------------------------------------------

    def add_span(self, event: Dict[str, Any]) -> None:
        tid = event.get("trace_id")
        if not tid:
            return
        with self._cv:
            if tid in self._dropped:
                self._count_drop("late")
                return
            tr = self._traces.get(tid)
            if tr is None:
                tr = {"spans": [], "bytes": 0,
                      "start": event.get("time", 0.0), "end": None,
                      "root": None, "done": False, "keep_reason": None}
                self._traces[tid] = tr
                self.total_traces += 1
            b = _span_bytes(event)
            tr["spans"].append(event)
            tr["bytes"] += b
            self._bytes += b
            t0 = event.get("time")
            if t0 is not None and t0 < tr["start"]:
                tr["start"] = t0
            # root = parentless span, or the proxy's ingress span (its
            # parent is a REMOTE span from the client's traceparent that
            # will never arrive here)
            if not event.get("parent_span_id") \
                    or (event.get("attributes") or {}).get("ingress"):
                tr["root"] = event
            root = tr["root"]
            if not tr["done"] and root is not None \
                    and root.get("end_time") is not None:
                self._complete(tid, tr)
            self._evict()
            self._cv.notify_all()

    def _complete(self, tid: str, tr: Dict[str, Any]) -> None:
        tr["done"] = True
        root = tr["root"]
        tr["end"] = root.get("end_time")
        reason = self._decide(tr)
        if reason is None:
            self._drop(tid, "sampled")
            return
        tr["keep_reason"] = reason
        self.kept_traces += 1
        self._completed.append(tid)

    def _decide(self, tr: Dict[str, Any]) -> Optional[str]:
        """Tail-sampling policy -> keep reason, or None to drop."""
        recovered = None
        for span in tr["spans"]:
            hit = _KEEP_ALWAYS_NAMES.get(span.get("name"))
            if hit:
                # a failover/preempt span's own error attribute is the
                # RECOVERED cause (the stream went on) — the trace only
                # classifies "error" when some other span failed
                recovered = recovered or hit
                continue
            attrs = span.get("attributes") or {}
            if attrs.get("error"):
                return "error"
        if recovered:
            return recovered
        root = tr["root"]
        dur = (root.get("end_time") or 0.0) - (root.get("time") or 0.0)
        attrs = root.get("attributes") or {}
        slow_s = attrs.get("slo_target")
        if not slow_s:
            # the per-deployment SLO rides the route span, not the root
            for span in tr["spans"]:
                slow_s = (span.get("attributes") or {}).get("slo_target")
                if slow_s:
                    break
        slow_s = slow_s or self._slow_s
        try:
            if dur > float(slow_s):
                return "slow"
        except (TypeError, ValueError):
            if dur > self._slow_s:
                return "slow"
        if self._rng.random() < self._sample_rate:
            return "sampled"
        return None

    def _drop(self, tid: str, reason: str) -> None:
        tr = self._traces.pop(tid, None)
        if tr is not None:
            self._bytes -= tr["bytes"]
        self._dropped[tid] = None
        while len(self._dropped) > _TOMBSTONES_MAX:
            self._dropped.popitem(last=False)
        self._count_drop(reason)

    def _count_drop(self, reason: str) -> None:
        if reason == "sampled":
            self.dropped_sampled += 1
        elif reason == "evicted":
            self.dropped_evicted += 1
        try:
            from ..util.tracing import TRACES_DROPPED
            TRACES_DROPPED.inc(tags={"reason": reason})
        except Exception:  # noqa: BLE001 — metrics must not break intake
            pass

    def _evict(self) -> None:
        # completed traces go first (oldest kept), then oldest active —
        # an in-flight trace is only sacrificed when nothing else remains
        while self._bytes > self._max_bytes and self._traces:
            if self._completed:
                tid = self._completed.popleft()
                self._base += 1
                if tid not in self._traces:
                    continue
            else:
                tid = next(iter(self._traces))
            self._drop(tid, "evicted")

    # ---- queries -------------------------------------------------------------

    def _summary(self, tid: str, tr: Dict[str, Any]) -> Dict[str, Any]:
        root = tr["root"] or (tr["spans"][0] if tr["spans"] else {})
        attrs = root.get("attributes") or {}
        deployment = attrs.get("deployment", "")
        session = attrs.get("session", "")
        request_id = attrs.get("request_id", "")
        for span in tr["spans"]:
            a = span.get("attributes") or {}
            deployment = deployment or a.get("deployment", "")
            session = session or a.get("session", "")
            request_id = request_id or a.get("request_id", "")
        end = tr["end"]
        return {"trace_id": tid, "name": root.get("name", ""),
                "start": tr["start"], "end": end,
                "duration_s": (end - (root.get("time") or tr["start"]))
                if end is not None else None,
                "spans": len(tr["spans"]),
                "procs": len({s.get("pid") for s in tr["spans"]}),
                "nodes": len({s.get("node_id") for s in tr["spans"]}),
                "done": tr["done"], "keep_reason": tr["keep_reason"],
                "deployment": deployment, "session": session,
                "request_id": request_id}

    @staticmethod
    def _matches(summ: Dict[str, Any], request_id: Optional[str],
                 session: Optional[str],
                 deployment: Optional[str]) -> bool:
        if request_id and not str(summ.get("request_id", "")).startswith(
                request_id):
            return False
        if session and summ.get("session") != session:
            return False
        if deployment and summ.get("deployment") != deployment:
            return False
        return True

    def get(self, trace_id: str) -> Optional[Dict[str, Any]]:
        """Full trace (summary + spans sorted by start) by exact id or
        unique hex prefix — CLI ergonomics like the state API."""
        with self._cv:
            tr = self._traces.get(trace_id)
            tid = trace_id
            if tr is None:
                hits = [t for t in self._traces if t.startswith(trace_id)]
                if len(hits) != 1:
                    return None
                tid = hits[0]
                tr = self._traces[tid]
            out = self._summary(tid, tr)
            out["spans_detail"] = sorted(
                (dict(s) for s in tr["spans"]),
                key=lambda s: s.get("time", 0.0))
        return out

    def query(self, request_id: Optional[str] = None,
              session: Optional[str] = None,
              deployment: Optional[str] = None,
              slowest: Optional[int] = None,
              since: Optional[int] = None,
              limit: int = 50,
              follow_timeout: Optional[float] = None) -> Dict[str, Any]:
        """-> {"traces": [summaries], "cursor": next_since}.

        Pages over *completed kept* traces in completion order (LogStore
        cursor semantics); without ``since``, the newest ``limit``
        matches (tail). ``slowest`` instead returns the N slowest kept
        traces by root duration. ``follow_timeout`` long-polls for the
        next matching completion."""
        import time as _time

        limit = max(1, int(limit))
        deadline = (None if not follow_timeout
                    else _time.monotonic() + float(follow_timeout))
        while True:
            with self._cv:
                base = self._base
                order = list(self._completed)
                tail = base + len(order)
                if since is None:
                    start = base
                    scan = order
                else:
                    start = max(base, int(since))
                    scan = order[start - base:]
                summs = {tid: self._summary(tid, self._traces[tid])
                         for tid in scan if tid in self._traces}
            out: List[Dict[str, Any]] = []
            if slowest is not None:
                cands = [s for s in summs.values()
                         if self._matches(s, request_id, session,
                                          deployment)
                         and s.get("duration_s") is not None]
                cands.sort(key=lambda s: -s["duration_s"])
                return {"traces": cands[:max(1, int(slowest))],
                        "cursor": tail}
            if since is None:
                cursor = tail
                for tid in reversed(scan):
                    s = summs.get(tid)
                    if s and self._matches(s, request_id, session,
                                           deployment):
                        out.append(s)
                        if len(out) >= limit:
                            break
                out.reverse()
            else:
                cursor = tail
                for i, tid in enumerate(scan):
                    s = summs.get(tid)
                    if s and self._matches(s, request_id, session,
                                           deployment):
                        out.append(s)
                        if len(out) >= limit:
                            cursor = start + i + 1
                            break
            if out or deadline is None:
                return {"traces": out, "cursor": cursor}
            remaining = deadline - _time.monotonic()
            if remaining <= 0:
                return {"traces": out, "cursor": cursor}
            since = tail
            with self._cv:
                if self._base + len(self._completed) == tail:
                    self._cv.wait(remaining)

    def slowest_active(self) -> Optional[Dict[str, Any]]:
        """Oldest still-open trace (root span not yet ended) — surfaced
        in `ray_tpu top` as the live tail-latency suspect."""
        import time as _time

        with self._cv:
            best = None
            for tid, tr in self._traces.items():
                if tr["done"]:
                    continue
                if best is None or tr["start"] < best[1]["start"]:
                    best = (tid, tr)
            if best is None:
                return None
            return {"trace_id": best[0], "name":
                    (best[1]["root"] or {}).get("name", "")
                    or (best[1]["spans"][0].get("name", "")
                        if best[1]["spans"] else ""),
                    "age_s": _time.time() - best[1]["start"]}

    def stats(self) -> Dict[str, Any]:
        with self._cv:
            active = sum(1 for tr in self._traces.values()
                         if not tr["done"])
            return {"traces": len(self._traces), "active": active,
                    "bytes": self._bytes,
                    "total_traces": self.total_traces,
                    "kept_traces": self.kept_traces,
                    "dropped_sampled": self.dropped_sampled,
                    "dropped_evicted": self.dropped_evicted,
                    "cursor": self._base + len(self._completed)}
