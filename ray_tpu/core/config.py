"""Typed, env-overridable flag registry.

Equivalent of the reference's RAY_CONFIG macro registry
(ref: src/ray/common/ray_config_def.h — 205 typed flags overridable via
RAY_<name> env vars and a cluster-wide system-config dict). Here: a plain
dataclass-like registry; override with RTPU_<NAME> env vars or
``init(system_config={...})``.
"""
from __future__ import annotations

import json
import os
from typing import Any, Dict


_DEFS: Dict[str, Any] = {}


def _define(name: str, default: Any) -> None:
    _DEFS[name] = default


# --- object store / serialization ---
_define("max_direct_call_object_size", 100 * 1024)  # inline threshold (ref: ray_config_def.h:213)
_define("task_args_inline_bytes_limit", 10 * 1024 * 1024)  # ref: ray_config_def.h:516
_define("object_store_memory", 2 * 1024**3)
_define("object_spilling_dir", "/tmp/ray_tpu_spill")
_define("min_spilling_size", 1 * 1024 * 1024)
_define("object_transfer_chunk_bytes", 5 * 1024 * 1024)  # ref: ray_config_def.h:348
# --- scheduling ---
_define("scheduler_spread_threshold", 0.5)  # hybrid policy (ref: ray_config_def.h:193)
_define("scheduler_top_k_fraction", 0.2)  # ref: ray_config_def.h:199-204
_define("worker_lease_timeout_s", 30.0)
_define("num_workers_soft_limit", 8)
_define("worker_prestart_count", 0)
_define("worker_startup_timeout_s", 60.0)
_define("worker_idle_timeout_s", 300.0)
# --- fault tolerance ---
_define("task_max_retries", 3)
_define("actor_max_restarts", 0)
_define("health_check_period_s", 1.0)
_define("health_check_timeout_s", 10.0)
_define("lineage_max_bytes", 256 * 1024 * 1024)
# --- gcs ---
_define("gcs_storage_path", "")  # non-empty => persist KV/tables to this dir (FT restart)
_define("task_events_max_buffered", 10000)
# --- misc ---
_define("log_dir", "/tmp/ray_tpu/logs")
_define("metrics_export_port", 0)


class Config:
    """Snapshot of config values; env vars RTPU_<NAME> override defaults,
    then an explicit system_config dict overrides both."""

    def __init__(self, system_config: Dict[str, Any] | None = None):
        self._values: Dict[str, Any] = {}
        for name, default in _DEFS.items():
            val = default
            env = os.environ.get("RTPU_" + name.upper())
            if env is not None:
                val = _parse(env, default)
            self._values[name] = val
        if system_config:
            for k, v in system_config.items():
                if k not in _DEFS:
                    raise ValueError(f"Unknown config key: {k}")
                self._values[k] = v

    def __getattr__(self, name: str):
        try:
            return self._values[name]
        except KeyError:
            raise AttributeError(name)

    def to_dict(self) -> Dict[str, Any]:
        return dict(self._values)


def _parse(env: str, default: Any) -> Any:
    if isinstance(default, bool):
        return env.lower() in ("1", "true", "yes")
    if isinstance(default, int):
        return int(env)
    if isinstance(default, float):
        return float(env)
    if isinstance(default, (dict, list)):
        return json.loads(env)
    return env


DEFAULT = Config()
