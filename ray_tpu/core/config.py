"""Typed, env-overridable flag registry.

Equivalent of the reference's RAY_CONFIG macro registry
(ref: src/ray/common/ray_config_def.h — 205 typed flags overridable via
RAY_<name> env vars and a cluster-wide system-config dict). Here: a plain
dataclass-like registry; override with RTPU_<NAME> env vars or
``init(system_config={...})``.
"""
from __future__ import annotations

import json
import os
from typing import Any, Dict


_DEFS: Dict[str, Any] = {}
_DESCRIPTIONS: Dict[str, str] = {}


def _define(name: str, default: Any, description: str = "") -> None:
    _DEFS[name] = default
    _DESCRIPTIONS[name] = description


def describe() -> Dict[str, Dict[str, Any]]:
    """Flag catalog: {name: {default, type, description, env}} — the
    analog of reading ray_config_def.h."""
    return {
        name: {"default": default,
               "type": type(default).__name__,
               "description": _DESCRIPTIONS.get(name, ""),
               "env": "RTPU_" + name.upper()}
        for name, default in _DEFS.items()
    }


# --- object store / serialization ---
_define("max_direct_call_object_size", 100 * 1024,
        "values at or under this inline into specs/results instead of the "
        "shared-memory store (ref: ray_config_def.h:213)")
_define("task_args_inline_bytes_limit", 10 * 1024 * 1024,
        "total inline-arg budget per task (ref: ray_config_def.h:516)")
_define("object_store_memory", 2 * 1024**3,
        "per-node shared-memory store capacity in bytes")
_define("object_spilling_dir", "/tmp/ray_tpu_spill",
        "disk spill directory; empty disables spilling")
_define("min_spilling_size", 1 * 1024 * 1024,
        "objects smaller than this are evicted rather than spilled")
_define("object_transfer_chunk_bytes", 5 * 1024 * 1024,
        "chunk size for inter-node object pulls/pushes "
        "(ref: ray_config_def.h:348)")
# --- scheduling ---
_define("scheduler_spread_threshold", 0.5,
        "hybrid policy: pack onto a node until this utilization, then "
        "spread (ref: ray_config_def.h:193)")
_define("scheduler_top_k_fraction", 0.2,
        "fraction of best-scoring nodes randomized over per decision "
        "(ref: ray_config_def.h:199)")
_define("worker_lease_timeout_s", 30.0,
        "how long a lease request waits for capacity before erroring")
_define("num_workers_soft_limit", 8,
        "per-node worker-pool size target; the idle reaper trims to it")
_define("worker_prestart_count", 0,
        "workers started eagerly at node bring-up")
_define("worker_startup_timeout_s", 60.0,
        "a worker that hasn't registered by then is declared failed")
_define("worker_idle_timeout_s", 300.0,
        "idle workers above the soft limit are reaped after this")
# --- runtime / rpc ---
_define("driver_pool_threads", 8,
        "DriverRuntime's shared thread pool (lease grants, await-ref "
        "futures, function export)")
_define("rpc_handler_threads", 4,
        "request-handler threads per RpcChannel (worker/agent channels)")
_define("node_server_threads", 16,
        "handler threads for a node's worker-facing RPC server")
_define("container_launcher",
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))),
            "scripts", "container_worker_launcher.sh"),
        "executable that launches a containerized worker: invoked as "
        "<launcher> <image> [run_options...] -- <worker cmd...>. The "
        "default is the repo's docker reference script; point it at a "
        "podman/k8s wrapper for other runtimes")
_define("capture_worker_logs", 1,
        "tee every worker's stdout/stderr over its node channel into the "
        "head's bounded log store (dashboard log view / state API); "
        "0 = only remote workers forward, for console display")
_define("log_store_max_bytes", 16 * 1024 * 1024,
        "byte budget for the head's attributed log store; oldest records "
        "evict first (ref: dashboard log retention)")
_define("trace_store_max_bytes", 8 * 1024 * 1024,
        "byte budget for the head's request-trace store; oldest traces "
        "evict first (counted in ray_tpu_traces_dropped_total)")
_define("trace_sample_rate", 1.0,
        "tail-sampling keep probability for ordinary completed traces; "
        "errors, failovers, preemptions and slow requests are ALWAYS "
        "kept regardless of this rate")
_define("trace_slow_threshold_s", 1.0,
        "completed traces slower than this are always tail-kept when the "
        "root span carries no per-deployment slo_target attribute")
_define("log_batch_lines", 200,
        "worker-side log forwarder flushes when this many lines are "
        "pending (or on the flush interval, whichever first)")
_define("log_flush_interval_s", 0.2,
        "worker-side log forwarder flush cadence")
_define("log_rate_limit_lines_per_s", 2000.0,
        "per-worker log forwarding budget; lines over it are DROPPED "
        "(counted in ray_tpu_logs_dropped_total) — capture must never "
        "block or OOM the task")
_define("agent_log_ring_lines", 2000,
        "per-worker log ring retained on each node agent (local triage "
        "when the head evicted or the link dropped batches)")
_define("log_to_driver", 1,
        "mirror remote workers' stdout/stderr onto the driver console "
        "with a colored (worker pid=, node=) prefix; 0 silences the "
        "mirror (records still reach the head store)")
_define("worker_task_prefetch", 16,
        "max same-signature tasks pushed onto one leased worker's queue "
        "(executed sequentially; only the lease's resources are held). "
        "Keeps workers fed under burst and lets RPC frames coalesce — "
        "set 1 to restore strict one-task-per-lease dispatch")
_define("agent_server_threads", 32,
        "handler threads for the head's agent-facing TCP server (blocking "
        "fetches must not starve worker_call relays)")
# --- decentralized dispatch (docs/DISPATCH.md) ---
_define("direct_actor_calls", 1,
        "steady-state actor calls bypass the head: the caller resolves "
        "placement once, then submits straight to the owning worker over "
        "a cached peer connection (0 = route everything through the head)")
_define("direct_worker_server", 1,
        "each worker listens on a direct-call socket so peers (other "
        "workers, the driver) can submit actor tasks without a head hop")
_define("direct_event_batch", 200,
        "direct-path task completions are batched into one "
        "task_events_batch message at this size (or the flush interval)")
_define("direct_event_flush_s", 0.5,
        "flush cadence for the batched direct-path task-event stream")
_define("head_event_shards", 8,
        "GCS task-event intake shards (per-shard ring + phase table + "
        "lock, keyed by task id) so event floods don't serialize on one "
        "lock; merged on read")
_define("refcount_shards", 16,
        "reference-counter shards keyed by object id")
_define("pg_placer_tick_s", 0.5,
        "parked placement groups re-check capacity at this cadence when "
        "no cluster event fires")
# --- fault tolerance ---
_define("task_max_retries", 3,
        "default automatic retries for worker-crash task failures")
_define("actor_max_restarts", 0,
        "default actor restart budget (0 = actors die with their worker)")
_define("health_check_period_s", 1.0,
        "head -> remote-agent heartbeat check cadence "
        "(ref: gcs_health_check_manager)")
_define("health_check_timeout_s", 10.0,
        "an agent silent for this long is declared dead and fenced")
_define("heartbeat_miss_threshold", 0,
        "declare a node dead only after this many consecutive missed "
        "heartbeat periods, when stricter than health_check_timeout_s "
        "(0 = timeout alone governs); every silent period counts in "
        "ray_tpu_heartbeat_misses_total{node}")
_define("lineage_max_bytes", 256 * 1024 * 1024,
        "lineage (resubmittable task specs) memory budget")
# --- gcs ---
_define("gcs_storage_path", "",
        "non-empty => persist KV/tables to this dir (head restart FT)")
_define("task_events_max_buffered", 20000,
        "task-event ring size backing the state API / timeline (a task "
        "now emits SUBMITTED/SCHEDULED/RUNNING/FINISHED, ~4 events)")
# --- misc ---
_define("log_dir", "/tmp/ray_tpu/logs",
        "worker/agent log directory")
_define("metrics_export_port", 0,
        "non-zero => Prometheus exposition server on this port")
_define("metrics_export_interval_s", 1.0,
        "cadence at which worker processes ship metric deltas to the "
        "head's /metrics exposition (agents piggyback on heartbeat). "
        "Workers read this from their own environment, so set it via "
        "RTPU_METRICS_EXPORT_INTERVAL_S — init(system_config=...) only "
        "reaches the head process")


class Config:
    """Snapshot of config values; env vars RTPU_<NAME> override defaults,
    then an explicit system_config dict overrides both."""

    def __init__(self, system_config: Dict[str, Any] | None = None):
        self._values: Dict[str, Any] = {}
        for name, default in _DEFS.items():
            val = default
            env = os.environ.get("RTPU_" + name.upper())
            if env is not None:
                val = _parse(env, default)
            self._values[name] = val
        if system_config:
            for k, v in system_config.items():
                if k not in _DEFS:
                    raise ValueError(f"Unknown config key: {k}")
                self._values[k] = v

    def __getattr__(self, name: str):
        try:
            return self._values[name]
        except KeyError:
            raise AttributeError(name)

    def to_dict(self) -> Dict[str, Any]:
        return dict(self._values)


def _parse(env: str, default: Any) -> Any:
    if isinstance(default, bool):
        return env.lower() in ("1", "true", "yes")
    if isinstance(default, int):
        return int(env)
    if isinstance(default, float):
        return float(env)
    if isinstance(default, (dict, list)):
        return json.loads(env)
    return env


DEFAULT = Config()
