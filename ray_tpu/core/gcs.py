"""GCS — global control service (control plane).

Equivalent of the reference's GCS server (ref: src/ray/gcs/gcs_server/
gcs_server.h:79) with its sub-managers: node table + health
(gcs_node_manager.cc, gcs_health_check_manager.h:39), actor directory +
lifecycle FSM (gcs_actor_manager.cc:246,271; src/ray/design_docs/
actor_states.rst), internal KV (gcs_kv_manager.cc), pubsub
(src/ray/pubsub/publisher.h:307), job table (gcs_job_manager.cc), placement
groups with 2-phase bundle commit (gcs_placement_group_manager.cc), and task
events (gcs_task_manager.h:61).

This runs in-process on the head (driver) — the single-controller model a TPU
pod already assumes — with optional directory-backed persistence standing in
for the Redis-backed fault-tolerance store (ref: store_client/
redis_store_client.h). Remote hosts reach it over the RpcChannel control
plane.
"""
from __future__ import annotations

import enum
import os
import pickle
import threading
import time
from collections import defaultdict, deque
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from ..devtools.locks import instrumented_lock
from ..util import metrics as _metrics
from .ids import ActorId, JobId, NodeId, PlacementGroupId, TaskId, WorkerId
from .resources import ResourceSet
from .task_spec import TaskSpec

# task-lifecycle phase latencies, derived from the state-transition
# event stream (ref: src/ray/stats/metric_defs.cc task latency metrics;
# gcs_task_manager.h:61). Tagged by task name so one straggling function
# is visible next to its siblings; cardinality = #distinct remote fns.
_H_SUBMIT_TO_SCHED = _metrics.Histogram(
    "ray_tpu_task_submit_to_sched_seconds",
    "submit -> node-picked scheduling latency", tag_keys=("name",))
_H_QUEUE_WAIT = _metrics.Histogram(
    "ray_tpu_task_queue_wait_seconds",
    "node-picked -> RUNNING queue/lease wait", tag_keys=("name",))
_H_EXEC = _metrics.Histogram(
    "ray_tpu_task_exec_seconds",
    "RUNNING -> FINISHED/FAILED execution time", tag_keys=("name",))

# phase marks outlive the bounded event ring but must stay bounded too:
# tasks that never reach a terminal state are evicted oldest-first
_PHASE_MARKS_MAX = 20000


class ActorState(enum.Enum):
    # ref: src/ray/design_docs/actor_states.rst
    DEPENDENCIES_UNREADY = 0
    PENDING_CREATION = 1
    ALIVE = 2
    RESTARTING = 3
    DEAD = 4


@dataclass
class ActorInfo:
    actor_id: ActorId
    name: str  # "" if unnamed
    namespace: str
    job_id: JobId
    state: ActorState
    creation_spec: TaskSpec
    max_restarts: int
    num_restarts: int = 0
    node_id: Optional[NodeId] = None
    worker_id: Optional[WorkerId] = None
    death_cause: str = ""
    detached: bool = False


@dataclass
class NodeInfo:
    node_id: NodeId
    total_resources: ResourceSet
    labels: Dict[str, str] = field(default_factory=dict)
    alive: bool = True
    last_heartbeat: float = field(default_factory=time.monotonic)
    # planned capacity loss (docs/FAULT_TOLERANCE.md "Elasticity"): a
    # preemption notice arrived — the node is still ALIVE and serving,
    # but the scheduler stops placing new work on it and workloads that
    # subscribed to the "node" channel drain/resize before the axe
    draining: bool = False
    preempt_deadline: float = 0.0  # monotonic; 0 = no notice


@dataclass
class JobInfo:
    job_id: JobId
    driver_pid: int
    start_time: float = field(default_factory=time.time)
    end_time: float = 0.0


@dataclass
class PlacementGroupInfo:
    pg_id: PlacementGroupId
    bundles: List[ResourceSet]
    strategy: str
    state: str = "PENDING"  # PENDING | CREATED | REMOVED | RESCHEDULING
    bundle_nodes: List[Optional[NodeId]] = field(default_factory=list)
    name: str = ""


class Pubsub:
    """In-process pub/sub with per-channel subscriber callbacks.
    (ref: src/ray/pubsub/publisher.h:307 — long-poll mailboxes; here the
    subscribers are in-process or bridged over RpcChannel notify)."""

    def __init__(self):
        self._subs: Dict[str, List[Callable[[Any], None]]] = defaultdict(list)
        self._lock = instrumented_lock("gcs.pubsub")

    def subscribe(self, channel: str, cb: Callable[[Any], None]) -> Callable[[], None]:
        with self._lock:
            self._subs[channel].append(cb)

        def _unsub():
            with self._lock:
                try:
                    self._subs[channel].remove(cb)
                except ValueError:
                    pass

        return _unsub

    def publish(self, channel: str, msg: Any) -> None:
        with self._lock:
            subs = list(self._subs.get(channel, ()))
        for cb in subs:
            try:
                cb(msg)
            except Exception:
                pass


class _EventShard:
    """One shard of the task-event intake: ring slice + monotonic counts
    + phase-mark table, with its own lock."""

    __slots__ = ("lock", "events", "counts", "phase_marks", "marks_max")

    def __init__(self, index: int, maxlen: int, marks_max: int):
        self.lock = instrumented_lock(f"gcs.events.s{index}")
        self.events: deque = deque(maxlen=maxlen)
        self.counts: Dict[str, int] = {}
        self.phase_marks: Dict[str, tuple] = {}
        self.marks_max = max(64, marks_max)


class Gcs:
    def __init__(self, storage_path: str = "", config=None):
        self._lock = instrumented_lock("gcs.tables", reentrant=True)
        self.pubsub = Pubsub()
        self._nodes: Dict[NodeId, NodeInfo] = {}
        self._jobs: Dict[JobId, JobInfo] = {}
        self._actors: Dict[ActorId, ActorInfo] = {}
        self._named_actors: Dict[tuple, ActorId] = {}  # (namespace, name) -> id
        self._kv: Dict[str, Dict[str, bytes]] = defaultdict(dict)  # namespace -> k -> v
        self._pgs: Dict[PlacementGroupId, PlacementGroupInfo] = {}
        # ring sized from the runtime's config (was hardcoded 10000 and
        # ignored the flag): SUBMITTED/SCHEDULED roughly doubled
        # events-per-task, so the default doubled with it — timeline()
        # slices keep the same effective task history as before
        if config is None:
            from .config import DEFAULT as config

        # event intake is SHARDED by task id (docs/DISPATCH.md): each
        # shard owns a ring slice + phase-mark table + lock, so a flood of
        # completion events from many clients doesn't serialize on one
        # lock; task_events() merges by timestamp on (rare) reads. One
        # task's events always land in one shard, keeping its
        # state-transition chain ordered.
        n_shards = max(1, int(getattr(config, "head_event_shards", 8)))
        per_shard = max(64, int(config.task_events_max_buffered) // n_shards)
        self._event_shards = [
            _EventShard(i, per_shard, _PHASE_MARKS_MAX // n_shards)
            for i in range(n_shards)]
        # attributed worker log records (stdout/stderr/structured),
        # byte-budgeted with long-poll follow — the `ray logs` analog
        # (ref: dashboard/modules/log/log_manager.py; gcs as the index)
        from .log_store import LogStore
        from .trace_store import TraceStore

        self.logs = LogStore(max_bytes=int(config.log_store_max_bytes))
        self.traces = TraceStore(
            max_bytes=int(config.trace_store_max_bytes),
            sample_rate=float(config.trace_sample_rate),
            slow_threshold_s=float(config.trace_slow_threshold_s))
        self._storage_path = storage_path
        # set by the Runtime: asks the scheduler to (re)create an actor
        self.schedule_actor_cb: Optional[Callable[[ActorInfo], None]] = None
        self._dirty = threading.Event()
        self._stop_flusher = threading.Event()
        self._flush_file_lock = instrumented_lock("gcs.flush_file")
        if storage_path:
            os.makedirs(storage_path, exist_ok=True)
            self._load()
            # debounced table snapshots (the Redis-write analog, ref:
            # redis_store_client.h; gcs_table_storage.cc)
            threading.Thread(target=self._flush_loop, daemon=True,
                             name="gcs-flusher").start()

    def _mark_dirty(self) -> None:
        if self._storage_path:
            self._dirty.set()

    def _flush_loop(self) -> None:
        while not self._stop_flusher.is_set():
            if self._dirty.wait(timeout=0.5):
                self._dirty.clear()
                self.flush()
            else:
                continue

    def flush(self) -> None:
        """Write the actor/job/pg tables + named index to disk atomically.
        The pickle happens under the table lock (records are mutated in
        place by the FSM — a copy of the dict alone would tear) and the
        file write is serialized so stop() can't interleave with the
        flusher thread."""
        if not self._storage_path:
            return
        try:
            with self._lock:
                blob = pickle.dumps({
                    "actors": dict(self._actors),
                    "named_actors": dict(self._named_actors),
                    "jobs": dict(self._jobs),
                    "pgs": dict(self._pgs),
                })
            with self._flush_file_lock:
                fname = os.path.join(self._storage_path, "tables.pkl")
                with open(fname + ".tmp", "wb") as f:
                    f.write(blob)
                os.replace(fname + ".tmp", fname)
        except Exception:
            self._dirty.set()  # retry on the next flusher tick

    def stop(self) -> None:
        self._stop_flusher.set()
        self.flush()

    # ---- node table ----------------------------------------------------------

    def register_node(self, info: NodeInfo) -> None:
        # node membership is NOT persisted (rebuilt by re-registration on
        # restart), so no dirty mark
        with self._lock:
            self._nodes[info.node_id] = info
        self.pubsub.publish("node", ("ALIVE", info.node_id))

    def mark_node_preempting(self, node_id: NodeId, grace_s: float,
                             reason: str = "") -> None:
        """Planned-capacity node event, DISTINCT from fencing: the node
        is still alive for ``grace_s`` more seconds. Publishes
        ``("PREEMPTING", node_id, grace_s)`` on the "node" channel so
        live workloads (pipeline engines, the serve control loop) can
        drain/hand off/resize before the kill lands. Idempotent per
        notice window."""
        with self._lock:
            info = self._nodes.get(node_id)
            if info is None or not info.alive:
                return
            if info.draining:
                return  # one notice per axe; re-deliveries are no-ops
            info.draining = True
            info.preempt_deadline = time.monotonic() + max(0.0, grace_s)
        self.pubsub.publish("node", ("PREEMPTING", node_id, grace_s))

    def mark_node_dead(self, node_id: NodeId, reason: str = "") -> None:
        with self._lock:
            info = self._nodes.get(node_id)
            if info is None or not info.alive:
                return
            info.alive = False
        self.pubsub.publish("node", ("DEAD", node_id))
        # fail over actors that lived on this node
        for actor in self.actors_on_node(node_id):
            self.on_actor_failure(actor.actor_id,
                                  f"node {node_id.hex()[:8]} died: {reason}")

    def heartbeat(self, node_id: NodeId) -> None:
        with self._lock:
            info = self._nodes.get(node_id)
            if info:
                info.last_heartbeat = time.monotonic()

    def nodes(self) -> List[NodeInfo]:
        with self._lock:
            return list(self._nodes.values())

    def alive_nodes(self) -> List[NodeInfo]:
        with self._lock:
            return [n for n in self._nodes.values() if n.alive]

    # ---- job table -----------------------------------------------------------

    def register_job(self, info: JobInfo) -> None:
        with self._lock:
            self._jobs[info.job_id] = info
        self._mark_dirty()

    def finish_job(self, job_id: JobId) -> None:
        with self._lock:
            if job_id in self._jobs:
                self._jobs[job_id].end_time = time.time()
        self._mark_dirty()

    # ---- actor directory + FSM ----------------------------------------------

    def register_actor(self, info: ActorInfo) -> None:
        with self._lock:
            if info.name:
                key = (info.namespace, info.name)
                if key in self._named_actors:
                    existing = self._actors.get(self._named_actors[key])
                    if existing and existing.state != ActorState.DEAD:
                        # checked before inserting the record so a rejected
                        # registration leaves no orphan actor entry
                        raise ValueError(f"Actor name {info.name!r} already taken")
                self._named_actors[key] = info.actor_id
            self._actors[info.actor_id] = info
        self._mark_dirty()
        self.pubsub.publish("actor", (info.actor_id, info.state))

    def set_actor_state(self, actor_id: ActorId, state: ActorState,
                        node_id: Optional[NodeId] = None,
                        worker_id: Optional[WorkerId] = None,
                        death_cause: str = "") -> None:
        with self._lock:
            info = self._actors.get(actor_id)
            if info is None:
                return
            info.state = state
            if node_id is not None:
                info.node_id = node_id
            if worker_id is not None:
                info.worker_id = worker_id
            if death_cause:
                info.death_cause = death_cause
        self._mark_dirty()
        self.pubsub.publish("actor", (actor_id, state))

    def on_actor_failure(self, actor_id: ActorId, cause: str) -> None:
        """Actor FSM edge: ALIVE/PENDING -> RESTARTING (if budget) or DEAD.
        (ref: gcs_actor_manager.cc OnActorWorkerDead / restart logic)"""
        with self._lock:
            info = self._actors.get(actor_id)
            if info is None or info.state == ActorState.DEAD:
                return
            if info.max_restarts != 0 and (
                info.max_restarts < 0 or info.num_restarts < info.max_restarts
            ):
                info.num_restarts += 1
                info.state = ActorState.RESTARTING
                info.death_cause = cause
                restart = True
            else:
                info.state = ActorState.DEAD
                info.death_cause = cause
                restart = False
        self._mark_dirty()
        self.pubsub.publish("actor", (actor_id, info.state))
        if restart and self.schedule_actor_cb is not None:
            self.schedule_actor_cb(info)

    def get_actor(self, actor_id: ActorId) -> Optional[ActorInfo]:
        with self._lock:
            return self._actors.get(actor_id)

    def get_named_actor(self, name: str, namespace: str) -> Optional[ActorInfo]:
        with self._lock:
            aid = self._named_actors.get((namespace, name))
            return self._actors.get(aid) if aid else None

    def actors_on_node(self, node_id: NodeId) -> List[ActorInfo]:
        with self._lock:
            return [a for a in self._actors.values()
                    if a.node_id == node_id
                    and a.state in (ActorState.ALIVE, ActorState.PENDING_CREATION,
                                    ActorState.RESTARTING)]

    def list_actors(self) -> List[ActorInfo]:
        with self._lock:
            return list(self._actors.values())

    # ---- internal KV (function table, cluster metadata) ----------------------

    def kv_put(self, key: str, value: bytes, namespace: str = "default",
               overwrite: bool = True) -> bool:
        with self._lock:
            ns = self._kv[namespace]
            if not overwrite and key in ns:
                return False
            ns[key] = value
        if self._storage_path:
            self._persist_kv(namespace, key, value)
        return True

    def kv_get(self, key: str, namespace: str = "default") -> Optional[bytes]:
        with self._lock:
            return self._kv[namespace].get(key)

    def kv_del(self, key: str, namespace: str = "default") -> None:
        with self._lock:
            self._kv[namespace].pop(key, None)

    def kv_keys(self, prefix: str = "", namespace: str = "default") -> List[str]:
        with self._lock:
            return [k for k in self._kv[namespace] if k.startswith(prefix)]

    # ---- placement groups ----------------------------------------------------

    def register_pg(self, info: PlacementGroupInfo) -> None:
        with self._lock:
            self._pgs[info.pg_id] = info
        self._mark_dirty()

    def get_pg(self, pg_id: PlacementGroupId) -> Optional[PlacementGroupInfo]:
        with self._lock:
            return self._pgs.get(pg_id)

    def list_pgs(self) -> List[PlacementGroupInfo]:
        with self._lock:
            return list(self._pgs.values())

    # ---- task events (timeline / state API backing store) --------------------

    def _event_shard(self, event: dict) -> _EventShard:
        tid = event.get("task_id") or event.get("trace_id") or ""
        return self._event_shards[hash(tid) % len(self._event_shards)]

    def add_task_event(self, event: dict) -> None:
        shard = self._event_shard(event)
        if event.get("state") == "SPAN" and event.get("trace_id"):
            # spans additionally feed the tail-sampled trace store (the
            # shard ring keeps them too, for timeline() flow arrows)
            self.traces.add_span(event)
        observe = None  # (histogram, seconds, name) — fired outside locks
        with shard.lock:
            shard.events.append(event)
            st = event.get("state", "?")
            shard.counts[st] = shard.counts.get(st, 0) + 1
            tid = event.get("task_id")
            t = event.get("time")
            if tid and isinstance(t, (int, float)):
                observe = self._mark_phase(shard, tid, st, float(t),
                                           event.get("name", ""))
        if observe is not None:
            hist, dt, name = observe
            hist.observe(dt, tags={"name": name})

    @staticmethod
    def _mark_phase(shard: _EventShard, tid: str, state: str, t: float,
                    name: str):
        """SUBMITTED -> SCHEDULED -> RUNNING -> FINISHED/FAILED phase
        durations. Called under the shard lock; returns the observation
        to make (metric locks must not nest inside the table lock)."""
        prev = shard.phase_marks.get(tid)
        out = None
        if state in ("FINISHED", "FAILED"):
            shard.phase_marks.pop(tid, None)
            if prev is not None and prev[0] == "RUNNING":
                out = (_H_EXEC, max(0.0, t - prev[1]), prev[2] or name)
            return out
        if state not in ("SUBMITTED", "SCHEDULED", "RUNNING"):
            return None
        if prev is not None:
            pstate, pt, pname = prev
            name = name or pname
            if state == "SCHEDULED" and pstate == "SUBMITTED":
                out = (_H_SUBMIT_TO_SCHED, max(0.0, t - pt), name)
            elif state == "RUNNING" and pstate in ("SUBMITTED", "SCHEDULED"):
                # actor tasks skip SCHEDULED (direct push): their queue
                # wait spans from submission
                out = (_H_QUEUE_WAIT, max(0.0, t - pt), name)
        elif len(shard.phase_marks) >= shard.marks_max:
            shard.phase_marks.pop(next(iter(shard.phase_marks)))
        shard.phase_marks[tid] = (state, t, name)
        return out

    def add_task_events(self, events: List[dict]) -> None:
        """Batched intake for the direct-dispatch completion stream (one
        message per flush interval instead of per-call traffic)."""
        for ev in events:
            if isinstance(ev, dict):
                self.add_task_event(ev)

    def task_event_counts(self) -> Dict[str, int]:
        """Monotonic per-state totals (unlike the bounded ring buffer,
        these never decrease — safe to export as Prometheus counters)."""
        out: Dict[str, int] = {}
        for shard in self._event_shards:
            with shard.lock:
                for k, v in shard.counts.items():
                    out[k] = out.get(k, 0) + v
        return out

    def task_events(self) -> List[dict]:
        """Merged view over the intake shards, timestamp-ordered (reads
        are rare — dashboards/state API; writes are the hot path)."""
        merged: List[dict] = []
        for shard in self._event_shards:
            with shard.lock:
                merged.extend(shard.events)
        merged.sort(key=lambda e: e.get("time", 0.0))
        return merged

    # ---- persistence (GCS fault-tolerance stand-in) --------------------------

    def _persist_kv(self, namespace: str, key: str, value: bytes) -> None:
        try:
            fname = os.path.join(self._storage_path, "kv.pkl")
            with self._lock:
                snapshot = {ns: dict(kv) for ns, kv in self._kv.items()}
            with open(fname + ".tmp", "wb") as f:
                pickle.dump(snapshot, f)
            os.replace(fname + ".tmp", fname)
        except Exception:
            pass

    def _load(self) -> None:
        fname = os.path.join(self._storage_path, "kv.pkl")
        if os.path.exists(fname):
            try:
                with open(fname, "rb") as f:
                    data = pickle.load(f)
                self._kv = defaultdict(dict, data)
            except Exception:
                pass
        tname = os.path.join(self._storage_path, "tables.pkl")
        if os.path.exists(tname):
            try:
                with open(tname, "rb") as f:
                    tables = pickle.load(f)
            except Exception:
                return
            self._jobs = tables.get("jobs", {})
            self._pgs = tables.get("pgs", {})
            self._actors = tables.get("actors", {})
            self._named_actors = tables.get("named_actors", {})
            # workers died with the old head: every actor that was running
            # is gone. Detached actors keep their creation spec and go to
            # RESTARTING so the new runtime can revive them (ref:
            # gcs_server.cc:521 restart path + actor_states.rst); normal
            # actors die with their job.
            for info in self._actors.values():
                if info.state == ActorState.DEAD:
                    continue
                if info.detached:
                    info.state = ActorState.RESTARTING
                    info.num_restarts = 0
                    info.node_id = None
                    info.worker_id = None
                    info.death_cause = "head restarted"
                else:
                    info.state = ActorState.DEAD
                    info.death_cause = "lost in head restart"
            for pg in self._pgs.values():
                if pg.state not in ("REMOVED",):
                    pg.state = "RESCHEDULING"
                    pg.bundle_nodes = [None] * len(pg.bundles)

    def detached_actors_to_revive(self) -> List[ActorInfo]:
        with self._lock:
            return [a for a in self._actors.values()
                    if a.detached and a.state == ActorState.RESTARTING
                    and a.node_id is None]
