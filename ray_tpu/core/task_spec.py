"""Task specification — the unit handed from submitter to scheduler to worker.

Equivalent of the reference's TaskSpecification
(ref: src/ray/common/task/task_spec.h; protobuf common.proto TaskSpec).
Args follow the reference's inlining rule: top-level ObjectRef args are
resolved by the executing worker; plain values ≤ the inline threshold travel
inside the spec, larger ones are promoted to the object store by the caller.
"""
from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from .ids import ActorId, JobId, NodeId, ObjectId, PlacementGroupId, TaskId, WorkerId
from .object_ref import ObjectRef


class TaskType(enum.Enum):
    NORMAL_TASK = 0
    ACTOR_CREATION_TASK = 1
    ACTOR_TASK = 2


# An argument is either an inline serialized value or a reference.
ARG_VALUE = 0
ARG_REF = 1
Arg = Tuple[int, Any]  # (ARG_VALUE, bytes) | (ARG_REF, ObjectRef)

# num_returns sentinel: the task is a generator streaming items back one
# at a time (ref: src/ray/protobuf/core_worker.proto:436
# ReportGeneratorItemReturns; num_returns="streaming")
STREAMING_RETURNS = -1


@dataclass
class SchedulingStrategy:
    """DEFAULT / SPREAD / node affinity / placement group.
    (ref: python/ray/util/scheduling_strategies.py)"""

    kind: str = "DEFAULT"  # DEFAULT | SPREAD | NODE_AFFINITY | PLACEMENT_GROUP
    node_id: Optional[NodeId] = None
    soft: bool = False
    placement_group_id: Optional[PlacementGroupId] = None
    bundle_index: int = -1  # -1 = any bundle


@dataclass
class TaskSpec:
    task_id: TaskId
    job_id: JobId
    task_type: TaskType
    func_id: str  # key into the GCS function table
    description: str  # human-readable fn/actor.method name
    args: List[Arg]
    kwargs: Dict[str, Arg]
    num_returns: int = 1
    resources: Dict[str, float] = field(default_factory=lambda: {"CPU": 1.0})
    max_retries: int = 0
    retry_exceptions: bool = False
    scheduling_strategy: SchedulingStrategy = field(default_factory=SchedulingStrategy)
    # direct dispatch: the submitting process's worker id = the actor
    # queue LANE this task is sequenced in (None = head-routed lane).
    # Per-caller FIFO is the ordering contract (ref:
    # direct_actor_task_submitter.h client-side sequencing); seq_no
    # counts within the lane.
    owner_id: Optional[WorkerId] = None
    # actor fields
    actor_id: Optional[ActorId] = None
    method_name: str = ""
    seq_no: int = 0  # client-side ordering for actor tasks
    max_restarts: int = 0
    max_concurrency: int = 1
    concurrency_group: str = ""
    # creation-task only: named group -> max concurrent calls
    # (ref: src/ray/core_worker/transport/concurrency_group_manager.cc)
    concurrency_groups: Optional[Dict[str, int]] = None
    is_async_actor: bool = False
    runtime_env: Optional[dict] = None
    # distributed tracing: (trace_id, parent_span_id) propagated from the
    # submitting context (ref: python/ray/util/tracing/ — the OTel
    # context-injection hooks; here spans ride the spec and land in the
    # GCS task-event stream)
    trace_ctx: Optional[tuple] = None

    def return_ids(self) -> List[ObjectId]:
        # STREAMING_RETURNS (-1): ids are minted per yielded item instead.
        # Memoized per task_id: the submit path asks three times per
        # task. The cache is keyed on the id because actor restart
        # copy.copy()s the creation spec and reassigns task_id — a bare
        # memo would hand the restarted task the ORIGINAL return ids.
        cached = self.__dict__.get("_rids")
        if cached is not None and cached[0] is self.task_id:
            return cached[1]
        rids = [ObjectId.for_task_return(self.task_id, i)
                for i in range(self.num_returns)]
        self.__dict__["_rids"] = (self.task_id, rids)
        return rids

    def arg_refs(self) -> List[ObjectRef]:
        refs = [a[1] for a in self.args if a[0] == ARG_REF]
        refs += [a[1] for a in self.kwargs.values() if a[0] == ARG_REF]
        return refs
