"""The per-process runtime: driver (head) and worker variants.

Equivalent of the reference's CoreWorker (ref: src/ray/core_worker/
core_worker.h:284 — Put :558, Get :665, Wait :704, SubmitTask :828,
CreateActor :849, SubmitActorTask :895) plus the direct task submitter
(transport/direct_task_transport.h:75) and the object directory.

Single-controller deviation (TPU-native stance): the head process owns the
control plane (GCS), the cluster view, and object ownership. Worker processes
run a thin WorkerRuntime that proxies the same API over their node channel —
the analog of the Cython binding calling into CoreWorker
(python/ray/_raylet.pyx:3111 submit_task).
"""
from __future__ import annotations

import collections
import contextvars
import hashlib
import os
import threading
import time
import weakref
from concurrent.futures import Future, ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

import cloudpickle

from .. import exceptions as exc
from ..devtools.locks import instrumented_lock
from ..perf.recorder import get_recorder as _get_recorder
from ..util import metrics as metrics_mod
from ..util.retry import RetryPolicy
from . import serialization
from .config import Config
from .gcs import ActorInfo, ActorState, Gcs, JobInfo, NodeInfo
from .ids import ActorId, JobId, NodeId, ObjectId, PlacementGroupId, TaskId, WorkerId
from .node import Node, WorkerHandle
from .object_ref import ObjectRef
from .object_store import SegmentReader
from .resources import ResourceSet, normalize, res_ge
from .scheduling_policy import NodeView, Scheduler
from .task_manager import ReferenceCounter, TaskManager
from .task_spec import (ARG_REF, ARG_VALUE, STREAMING_RETURNS,
                        SchedulingStrategy, TaskSpec, TaskType)

_runtime_lock = instrumented_lock("runtime.global_registry")
_runtime: Optional[object] = None

# fault-injection hook (ray_tpu.chaos): None until chaos.enable()
# installs an engine; the pull path pays one global is-None test
_CHAOS = None

_C_HEARTBEAT_MISSES = metrics_mod.Counter(
    "ray_tpu_heartbeat_misses_total",
    "health-check periods that elapsed without an agent heartbeat",
    tag_keys=("node",))

# elastic capacity (docs/FAULT_TOLERANCE.md "Elasticity"): every node
# that leaves after a preemption notice counts here — outcome=drained
# when it left with no busy workers (the notice worked), outcome=lost
# when the axe beat the drain and live work died with it
_C_PREEMPTIONS = metrics_mod.Counter(
    "ray_tpu_node_preemptions_total",
    "preemption-noticed nodes that left the cluster, by drain outcome",
    tag_keys=("outcome",))

# dispatch-fallback reconnect policy (util/retry.py): how long a failed
# direct-peer connect keeps the actor on the routed path before the next
# attempt — grows per consecutive failure, resets on success
_DIRECT_RECONNECT = RetryPolicy(initial_backoff_s=2.5, multiplier=2.0,
                                max_backoff_s=30.0, jitter=0.3)

# hot-path latency instruments (head side; the worker-side mirrors live
# in each worker's registry and ship to the head via metrics_push)
_H_GET_WAIT = metrics_mod.Histogram(
    "ray_tpu_get_wait_seconds",
    "blocking wait in ray_tpu.get() / fetch_one")
_H_RESULT_PUT = metrics_mod.Histogram(
    "ray_tpu_task_result_put_seconds",
    "head-side intake of a finished task's results",
    boundaries=metrics_mod.FAST_BOUNDARIES)
# decentralized dispatch (docs/DISPATCH.md): per-process counters for the
# two submission paths; worker processes' increments ship to the head via
# the metrics plane, so a cluster-wide scrape shows the split
_C_DIRECT = metrics_mod.Counter(
    "ray_tpu_task_direct_total",
    "actor tasks submitted on the direct path (no head hop)")
_C_ROUTED = metrics_mod.Counter(
    "ray_tpu_task_routed_total",
    "tasks submitted through the head (routed path)")


def dispatch_counts() -> Tuple[float, float]:
    """(direct, routed) submissions counted IN THIS PROCESS — the test
    hook for 'steady-state actor calls make zero head RPCs'."""
    return _C_DIRECT.total(), _C_ROUTED.total()


# flight recorder (ray_tpu.perf): dispatch decisions land in the
# per-process ring so a post-mortem bundle shows what was routed where
# in the seconds before an abort
_FLREC = _get_recorder()


def _rec_dispatch(path: str, spec) -> None:
    if _FLREC.enabled:
        _FLREC.record(f"dispatch.{path}", spec.description,
                      {"task": spec.task_id.hex()[:12]})


class ShardedLoop:
    """N worker threads, each owning a FIFO queue; work is keyed so every
    item with one key runs on one thread IN ORDER (docs/DISPATCH.md —
    the sharded head event loop).

    The agent channel multiplexes every remote worker onto ONE oneway
    lane; keying its intake (task_done / object_sealed / worker_call /
    worker_exit) by worker id spreads the head's dispatch work across
    cores while preserving the per-worker FIFO that the crash/completion
    protocol relies on."""

    def __init__(self, name: str, shards: int):
        import queue as _q

        self._queues = [_q.SimpleQueue() for _ in range(max(1, shards))]
        self._n = len(self._queues)
        for i, q in enumerate(self._queues):
            threading.Thread(target=self._run, args=(q,), daemon=True,
                             name=f"{name}-s{i}").start()

    def submit(self, key, fn, *args) -> None:
        self._queues[hash(key) % self._n].put((fn, args))

    @staticmethod
    def _run(q) -> None:
        import traceback as _tb

        while True:
            fn, args = q.get()
            try:
                fn(*args)
            except Exception:
                _tb.print_exc()


def set_runtime(rt) -> None:
    global _runtime
    with _runtime_lock:
        _runtime = rt


def get_runtime():
    if _runtime is None:
        raise RuntimeError("ray_tpu is not initialized; call ray_tpu.init() first.")
    return _runtime


def maybe_runtime():
    return _runtime


@dataclass
class RuntimeContext:
    job_id: JobId
    node_id: Optional[NodeId]
    worker_id: WorkerId
    task_id: Optional[TaskId] = None
    actor_id: Optional[ActorId] = None
    namespace: str = "default"

    def get_job_id(self):
        return self.job_id.hex()

    def get_node_id(self):
        return self.node_id.hex() if self.node_id else None

    def get_actor_id(self):
        return self.actor_id.hex() if self.actor_id else None


class _ObjShard:
    """One shard of the head's object state (docs/DISPATCH.md): the
    in-memory store, location directory, availability events, waiter
    lists, sizes, nested-result pins, and in-flight pull futures for the
    object ids hashing here — under one shard lock. Every object
    operation is single-oid, so shards never deadlock each other; only
    node-death sweeps iterate all shards."""

    __slots__ = ("lock", "mem", "dir", "events", "sizes", "waiters",
                 "nested", "pulls")

    def __init__(self, index: int):
        self.lock = instrumented_lock(f"runtime.obj.s{index}")
        self.mem: Dict[ObjectId, bytes] = {}
        self.dir: Dict[ObjectId, Set[NodeId]] = {}
        self.events: Dict[ObjectId, threading.Event] = {}
        self.sizes: Dict[ObjectId, int] = {}
        self.waiters: Dict[ObjectId, list] = {}
        self.nested: Dict[ObjectId, list] = {}
        self.pulls: Dict[ObjectId, Future] = {}


@dataclass
class _ActorRecord:
    info: ActorInfo
    seq: int = 0
    worker: Optional[WorkerHandle] = None
    node_id: Optional[NodeId] = None
    queued: List[TaskSpec] = field(default_factory=list)
    lock: Any = field(
        default_factory=lambda: instrumented_lock("runtime.actor_record"))
    # direct dispatch (docs/DISPATCH.md): placement epoch (bumped each
    # time the actor lands on a worker — the version stamp callers cache),
    # the driver's own direct-lane sequence counter, its in-flight direct
    # tasks (resubmitted via the head on worker/peer failure), and the
    # cached peer channel for remote-node workers
    epoch: int = 0
    dseq: int = 0
    # connection-era token for the direct lane: bumped on every new peer
    # channel (dseq restarts at 0 with it), carried in each direct_submit
    # frame so the worker's lane can distinguish a reconnected caller
    # (reset the lane) from a straggler frame of the dead connection
    # (drop it — its task was recovered through the routed path). Local
    # workers ride the node channel, which lives as long as the worker,
    # so their era never moves within an epoch.
    dlane: int = 0
    direct_inflight: Dict[TaskId, TaskSpec] = field(default_factory=dict)
    direct_chan: Any = None
    # negative cache for the peer connect: monotonic deadline before which
    # no reconnect is attempted (0.0 = try). Time-bounded, not permanent:
    # a transiently refused connect (accept backlog, listener busy) must
    # not strand the actor on the routed path for the whole epoch, while
    # a truly unreachable socket (cross-host) costs one failed connect
    # per window instead of one per call. The window grows per
    # consecutive failure on the shared reconnect policy (util/retry.py)
    # and resets on success / new placement epoch.
    direct_bad: float = 0.0
    direct_fails: int = 0


class DriverRuntime:
    """Head-process runtime: owns GCS, nodes, objects, and scheduling."""

    def __init__(self, resources: Optional[ResourceSet] = None,
                 num_nodes: int = 1,
                 config: Optional[Config] = None,
                 namespace: str = "default",
                 session_dir: Optional[str] = None):
        self.config = config or Config()
        self.job_id = JobId.from_random()
        self.worker_id = WorkerId.from_random()
        self.driver_task_id = TaskId.from_random()
        self.namespace = namespace
        self.session_dir = session_dir or os.path.join(
            "/tmp/ray_tpu", f"session_{int(time.time() * 1000)}_{os.getpid()}")
        os.makedirs(self.session_dir, exist_ok=True)
        self.gcs = Gcs(storage_path=self.config.gcs_storage_path,
                       config=self.config)
        self.gcs.register_job(JobInfo(job_id=self.job_id, driver_pid=os.getpid()))
        self.gcs.schedule_actor_cb = self._restart_actor
        self.gcs.pubsub.subscribe("actor", self._on_actor_state)
        self.gcs.pubsub.subscribe("node", self._on_node_state)
        self.scheduler = Scheduler(self.config.scheduler_spread_threshold)
        self.task_manager = TaskManager(self.config.lineage_max_bytes)
        self.refcount = ReferenceCounter(
            self._free_object, shards=int(self.config.refcount_shards))
        self.nodes: Dict[NodeId, Node] = {}
        # object state lives in per-oid shards (memory store, directory,
        # events, waiters, sizes, nested pins, pull dedup) — the head's
        # hottest tables no longer serialize on the big runtime lock
        self._oshards = [_ObjShard(i) for i in range(16)]
        self._no = len(self._oshards)
        # PG placement: one dedicated placer thread drains a FIFO of
        # pending groups (ref: gcs_placement_group_scheduler.cc — the GCS
        # schedules PGs from a single queue). A per-PG thread-pool task per
        # cluster event flooded the shared pool O(N^2) at 1k PGs.
        self._pg_cv = threading.Condition()
        self._pg_pending: "collections.deque[PlacementGroupId]" = collections.deque()
        self._pg_parked: Set[PlacementGroupId] = set()
        self._recovering: Set[ObjectId] = set()
        # attributed worker logs live in gcs.logs (LogStore); the mirror
        # prints remote workers' lines on the driver console with a
        # colored provenance prefix + repeated-line dedup (ref:
        # log_monitor.py -> driver stdout mirroring, `log_to_driver`)
        from ..util.logs import DriverMirror

        self._log_mirror = DriverMirror(
            enabled=bool(int(self.config.log_to_driver)))
        # compiled graphs (ray_tpu/cgraph): live graphs by id, the
        # actor-exclusivity ledger, and the cross-node channel routing
        # table (cid hex -> ("driver", dag, None, gid) |
        # ("worker", node, worker, gid))
        self._cgraphs: Dict[bytes, object] = {}
        self._cgraph_actors: Dict[bytes, bytes] = {}
        self._cgraph_routes: Dict[str, tuple] = {}
        self._generators: Dict[TaskId, dict] = {}
        self._released_generators: Set[TaskId] = set()
        self._reader = SegmentReader()
        self._actors: Dict[ActorId, _ActorRecord] = {}
        self._parked: List[TaskSpec] = []
        self._put_counter = 0
        self._fn_cache: Dict[int, str] = {}
        self._renv_cache: Dict[str, dict] = {}
        self.default_runtime_env: Optional[dict] = None  # job-level env
        self._lock = instrumented_lock("runtime.driver", reentrant=True)
        self._pool = ThreadPoolExecutor(
            max_workers=int(self.config.driver_pool_threads),
            thread_name_prefix="rt")
        # direct dispatch: steady-state actor calls skip the routed path
        # (task_manager / GCS events / lease machinery) and go straight to
        # the owning worker; see docs/DISPATCH.md
        self._direct_enabled = bool(int(self.config.direct_actor_calls))
        self._shutdown = False
        self._shutdown_lock = threading.Lock()
        self._shutdown_owner: Optional[int] = None
        threading.Thread(target=self._pg_placer_loop, daemon=True,
                         name="pg-placer").start()
        default_res = resources or {"CPU": float(os.cpu_count() or 1)}
        for i in range(num_nodes):
            self.add_node(dict(default_res))
        self.head_node_id = next(iter(self.nodes), None)
        # refs the driver receives INSIDE fetched values (borrows) must be
        # counted like refs it created via make_ref
        from .object_ref import _set_borrow_hook

        def _driver_borrow(ref: ObjectRef) -> None:
            self.refcount.add_local(ref.id)
            weakref.finalize(ref, self.refcount.remove_local, ref.id)

        _set_borrow_hook(_driver_borrow)
        # deterministic fault injection (RAY_TPU_CHAOS env): installs the
        # seeded drop/delay/kill hooks and starts the kill schedule
        from .. import chaos as _chaos_mod

        _chaos_mod.maybe_enable_from_env(runtime=self)
        self._revive_detached_actors()
        # head restart: PGs restored as RESCHEDULING (gcs restore path)
        # need a placement pass once nodes re-register
        with self._pg_cv:
            for pg in self.gcs.list_pgs():
                if pg.state in ("PENDING", "RESCHEDULING"):
                    self._pg_pending.append(pg.pg_id)
            self._pg_cv.notify()

    def _revive_detached_actors(self) -> None:
        """Head restart: re-create detached actors whose metadata survived
        in the persisted GCS tables (ref: gcs_server.cc:521 restart path;
        detached lifetime semantics)."""
        for info in self.gcs.detached_actors_to_revive():
            with self._lock:
                self._actors[info.actor_id] = _ActorRecord(info=info)
            try:
                self._restart_actor(info)
            except Exception:
                self.gcs.set_actor_state(info.actor_id, ActorState.DEAD,
                                         death_cause="revival failed")

    # ---- cluster membership --------------------------------------------------

    def enable_remote_nodes(self, host: str = "127.0.0.1", port: int = 0):
        """Start the TCP listener node agents join (the head half of the
        multi-host runtime; ref: gcs_server.h:79 node registration +
        node_manager.proto lease/transfer RPCs collapsed onto one duplex
        channel per agent). Returns the (host, port) address agents pass
        as --address."""
        from .rpc import RpcServer

        if getattr(self, "_remote_server", None) is not None:
            return self._remote_server.address
        # the agent channel multiplexes every remote worker onto one
        # oneway lane: shard its intake by worker id so dispatch work
        # parallelizes across cores with per-worker FIFO preserved
        self._agent_loop = ShardedLoop(
            "head-agent", min(8, (os.cpu_count() or 2) * 2))
        # one agent channel multiplexes every worker on that host; size the
        # pool so blocking fetches can't starve the worker_call relay
        self._remote_server = RpcServer(
            (host, port), self._make_agent_handler, family="AF_INET",
            num_handler_threads=int(self.config.agent_server_threads))
        # health monitor: remote nodes must keep heartbeating or be
        # declared dead even with the TCP channel still open (hung agent,
        # network partition) — ref: gcs_health_check_manager.h:39
        self._health_thread = threading.Thread(
            target=self._health_check_loop, daemon=True, name="health-check")
        self._health_thread.start()
        return self._remote_server.address

    def _health_check_loop(self) -> None:
        period = float(self.config.health_check_period_s)
        timeout = float(self.config.health_check_timeout_s)
        # consecutive-miss fencing: heartbeat_miss_threshold > 0 extends
        # the death bar to threshold*period when that is stricter than
        # timeout alone (docs/FAULT_TOLERANCE.md); every silent period
        # counts in ray_tpu_heartbeat_misses_total{node} either way
        threshold = int(self.config.heartbeat_miss_threshold)
        if threshold > 0:
            timeout = max(timeout, threshold * period)
        while not self._shutdown:
            time.sleep(period)
            now = time.monotonic()
            with self._lock:
                remote_ids = [nid for nid, n in self.nodes.items()
                              if getattr(n, "is_remote", False) and n.alive]
            for nid in remote_ids:
                info = next((i for i in self.gcs.nodes()
                             if i.node_id == nid), None)
                if info is None or not info.alive:
                    continue
                silent = now - info.last_heartbeat
                if silent > period:
                    _C_HEARTBEAT_MISSES.inc(
                        tags={"node": nid.hex()[:12]})
                if silent > timeout:
                    self.on_remote_node_lost(nid)

    def _make_agent_handler(self, channel):
        from .node import WorkerHandle
        from .remote_node import RemoteNode

        state = {"node": None}

        def handler(method: str, payload):
            node: Optional[RemoteNode] = state["node"]
            # job-submission plane: served to UNREGISTERED client channels
            # (a second process submitting work to this running head; ref:
            # dashboard/modules/job/job_manager.py REST surface)
            if method == "submit_job":
                from .. import jobs

                return jobs.submit_job(payload["entrypoint"],
                                       env=payload.get("env"),
                                       working_dir=payload.get("working_dir"))
            if method == "job_info":
                from .. import jobs

                return jobs.get_job_info(payload)
            if method == "list_jobs":
                from .. import jobs

                return jobs.list_jobs()
            if method == "list_nodes":
                # launcher/status plane (ref: state API list_nodes)
                return [{"node_id": n.node_id.hex(), "alive": n.alive,
                         "resources": dict(n.total_resources)}
                        for n in self.gcs.nodes()]
            if method == "perf_snapshot":
                # `ray_tpu top` plane: ONE RPC returns nodes + every
                # ray_tpu_* scalar + latency summaries (perf/snapshot.py)
                from ..perf.snapshot import head_snapshot

                return head_snapshot(self)
            # debugging plane, served to unregistered channels too so
            # `ray_tpu logs/stack/profile --address H:P` work against a
            # running head (ref: `ray logs` / `ray stack` CLI)
            if method == "logs_query":
                return self.query_logs(**(payload or {}))
            if method == "traces_query":
                return self.gcs.traces.query(**(payload or {}))
            if method == "trace_get":
                return self.gcs.traces.get(payload)
            if method == "trace_chrome":
                from ..util.state import _span_trace_events

                tr = self.gcs.traces.get(payload)
                return (_span_trace_events(list(tr.get("spans_detail", ())))
                        if tr else None)
            if method == "stack_report":
                return self.stack_report(
                    float((payload or {}).get("timeout", 5.0)))
            if method == "profile_worker":
                return self.profile_worker(
                    payload["worker_id"],
                    duration_s=float(payload.get("duration_s", 5.0)),
                    interval_s=float(payload.get("interval_s", 0.01)))
            if method == "stop_job":
                from .. import jobs

                return jobs.stop_job(payload)
            if method == "register_client":
                # Ray-Client plane (ref: python/ray/util/client/ server/
                # proxier.py): a REMOTE DRIVER attaches to this running
                # head; its channel speaks the same worker-call protocol
                # with byte-valued object transfer (no shared /dev/shm
                # across hosts). Holder refs key off the client id and are
                # dropped wholesale on disconnect.
                shell = _ClientShell(WorkerId.from_random())
                state["client"] = shell
                channel.on_close(
                    lambda cid=shell.worker_id:
                    self.refcount.release_holder(cid))
                return {"client_id": shell.worker_id.hex(),
                        "job_id": self.job_id.hex(),
                        "namespace": self.namespace}
            client = state.get("client")
            if client is not None:
                return self._handle_client_call(client, method, payload)
            if method == "register_node":
                node = RemoteNode(self, payload["node_id"],
                                  payload["resources"], self.config, channel,
                                  labels=payload.get("labels"))
                node.peer_addr = payload.get("object_server_addr")
                state["node"] = node
                with self._lock:
                    self.nodes[node.node_id] = node
                self.gcs.register_node(node.info())
                self._reschedule_parked()
                # new capacity: spill leases stuck behind full nodes
                self._spill_queued_leases()
                # the head's health cadence governs the agent's heartbeat
                # period — local agent config must not race a stricter head
                return {"health_check_period_s":
                        float(self.config.health_check_period_s)}
            if node is None:
                raise RuntimeError("agent sent a message before register_node")
            if not node.alive:
                # fenced-off node (declared dead by heartbeat timeout):
                # drop everything — its tasks were already rescheduled
                return None
            if method == "heartbeat":
                self.gcs.heartbeat(node.node_id)
                # agents piggyback their process's metric deltas (store
                # ops, RPC latency, user metrics) on the liveness signal
                if payload:
                    metrics_mod.merge_remote(
                        payload, node=node.node_id.hex()[:12])
                return None
            if method == "worker_register":
                node.on_remote_worker_register(
                    payload["worker_id"], payload.get("pid", 0),
                    direct_addr=payload.get("direct_addr"))
                return True
            if method == "worker_exit":
                # sharded with task_done on the same worker-id key: exit
                # processing must not overtake a completion already queued
                self._agent_loop.submit(
                    payload["worker_id"], node.on_remote_worker_exit,
                    payload["worker_id"], payload.get("error"))
                return None
            if method == "task_done":
                self._agent_loop.submit(payload["worker_id"],
                                        self._agent_task_done, node, payload)
                return None
            if method == "object_sealed":
                self._agent_loop.submit(
                    payload.get("worker_id") or payload["object_id"],
                    self._agent_object_sealed, node, payload)
                return None
            if method == "object_copy":
                oid = payload["object_id"]
                sh = self._oshard(oid)
                with sh.lock:
                    sh.dir.setdefault(oid, set()).add(node.node_id)
                return None
            if method == "fetch_for_agent":
                return self._fetch_for_agent(node, payload["object_id"],
                                             payload.get("timeout"),
                                             relay=payload.get("relay",
                                                               False))
            if method == "head_read_chunk":
                return self._read_local_chunk(payload["object_id"],
                                              payload["offset"],
                                              payload["length"])
            if method == "worker_call":
                if payload["method"] in ("metrics_push", "worker_log",
                                         "log_event", "task_events_batch"):
                    # always notify-relayed by the agent (no reply):
                    # sharded off the channel lane, keyed per worker
                    self._agent_loop.submit(
                        payload.get("worker_id") or 0,
                        self._agent_worker_call, node, payload)
                    return None
                return self._agent_worker_call(node, payload)
            raise ValueError(f"unknown agent message {method}")

        return handler

    def _agent_task_done(self, node, payload: dict) -> None:
        worker = node.get_worker(payload["worker_id"])
        if worker is not None:
            node.on_task_done(worker, payload["payload"])

    def _agent_object_sealed(self, node, payload: dict) -> None:
        self.on_object_sealed(payload["object_id"], node.node_id,
                              size=payload.get("size"))
        if payload.get("is_put") and payload.get("worker_id"):
            self.refcount.add_holder_ref(payload["object_id"],
                                         payload["worker_id"])

    def _agent_worker_call(self, node, payload: dict):
        from .node import WorkerHandle

        worker = node.get_worker(payload["worker_id"])
        if worker is None:
            # raced an exit notification; holder accounting still
            # needs the id, nothing else does
            worker = WorkerHandle(worker_id=payload["worker_id"],
                                  proc=None)  # type: ignore
        return self.handle_worker_call(node, worker, payload["method"],
                                       payload["payload"])

    def _fetch_for_agent(self, node, oid: ObjectId,
                         timeout: Optional[float], relay: bool = False):
        """Answer an agent's fetch: ("inline", bytes) for small objects,
        ("remote", [peer_addrs]) when other agents hold the only copies —
        the requester pulls chunks from them DIRECTLY (P2P, the head never
        touches the bytes; ref: object_manager.h:117) — or ("sized", n)
        when the head's own store has (or, with relay=True, pulls) a copy
        to serve via head_read_chunk."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while not relay:
            ev = self._event(oid)
            remaining = (None if deadline is None
                         else max(0.0, deadline - time.monotonic()))
            if not ev.wait(remaining):
                raise exc.GetTimeoutError(
                    f"Get timed out waiting for object {oid.hex()[:12]}")
            sh = self._oshard(oid)
            with sh.lock:
                data = sh.mem.get(oid)
                copies = list(sh.dir.get(oid, ()))
            if data is not None:
                return ("inline", data)
            peers = []
            head_local = False
            for nid in copies:
                n = self.nodes.get(nid)
                if n is None or not n.alive:
                    continue
                if not getattr(n, "is_remote", False):
                    head_local = True
                elif nid != node.node_id and getattr(n, "peer_addr", None):
                    peers.append(tuple(n.peer_addr))
            if head_local:
                break  # serve from the head's own store below
            if peers:
                return ("remote", peers)
            break  # copies lost or only on the requester: relay path
        res = self.fetch_one(oid, (None if deadline is None
                                   else max(0.0,
                                            deadline - time.monotonic())))
        if res[0] == "inline":
            return res
        return ("sized", res[2])  # agent pulls via head_read_chunk

    def _read_local_chunk(self, oid: ObjectId, offset: int, length: int):
        """Serve a chunk of a locally-stored object (transfer source side)."""
        from .object_store import read_store_chunk

        sh = self._oshard(oid)
        with sh.lock:
            copies = list(sh.dir.get(oid, ()))
        for nid in copies:
            n = self.nodes.get(nid)
            if n is None or not n.alive or getattr(n, "is_remote", False):
                continue
            chunk = read_store_chunk(n.store, self._reader, oid, offset,
                                     length)
            if chunk is not None:
                return chunk
        return None

    def on_preemption_notice(self, node_id: NodeId, grace_s: float,
                             reason: str = "") -> None:
        """Planned capacity loss: a provider preemption notice (or chaos
        ``preempt=`` schedule) says ``node_id`` dies in ``grace_s``
        seconds. The node stays ALIVE and keeps serving in-flight work,
        but (a) the scheduler stops placing new leases/bundles on it
        (``_views`` drain filter), (b) the GCS publishes a
        ``NODE_PREEMPTING`` event workloads subscribe to (pipeline
        engines resize, docs/FAULT_TOLERANCE.md), (c) the serve
        controller — when one is running — is told to drain the replicas
        living there, and (d) a remote agent gets a ``drain`` command so
        it exits cleanly once its workers are gone instead of waiting
        for the axe."""
        node = self.nodes.get(node_id)
        if node is None or not node.alive:
            return
        already = getattr(node, "draining", False)
        node.draining = True
        if already:
            return  # one notice per axe window
        self.gcs.mark_node_preempting(node_id, grace_s, reason)
        # queued-but-ungranted work must not start on a doomed node:
        # spill it back through the scheduler (other nodes or parked)
        self._spill_queued_leases(node=node, everything=True)
        if getattr(node, "is_remote", False):
            try:
                node.channel.notify("drain", {"grace_s": float(grace_s)})
            except Exception:
                pass
        self._notify_serve_drain(node_id, grace_s)

    def _notify_serve_drain(self, node_id: NodeId, grace_s: float) -> None:
        """Best-effort: hand the serve controller the actor ids living on
        the preempting node so it marks those replicas draining (router
        stops assigning new streams; in-flight ones finish or fail over
        before the node dies). The controller runs in a worker process
        and cannot subscribe to head pubsub itself."""
        from ..serve.controller import CONTROLLER_NAME

        try:
            info = self.gcs.get_named_actor(CONTROLLER_NAME, self.namespace)
            if info is None or info.state != ActorState.ALIVE:
                return
            ids = [a.actor_id.hex()
                   for a in self.gcs.actors_on_node(node_id)
                   if a.actor_id != info.actor_id]
            if not ids:
                return
            import ray_tpu

            ray_tpu.get_actor(CONTROLLER_NAME).drain_replicas.remote(
                ids, float(grace_s))
        except Exception:
            pass

    def _count_preempt_outcome(self, node) -> None:
        """Called exactly when a node leaves (lost channel or explicit
        removal): if it had a preemption notice, grade the drain."""
        if not getattr(node, "draining", False) \
                or getattr(node, "_preempt_counted", False):
            return
        node._preempt_counted = True
        with node._lock:
            busy = any(w.state in ("leased", "actor")
                       for w in node._workers.values())
        _C_PREEMPTIONS.inc(tags={"outcome": "lost" if busy else "drained"})

    def on_remote_node_lost(self, node_id: NodeId) -> None:
        """Agent channel dropped: fail in-flight work, restart actors
        (ref: gcs_node_manager.cc death broadcast)."""
        node = self.nodes.get(node_id)
        if node is None:
            return
        self._count_preempt_outcome(node)
        with node._lock:
            if not node.alive:
                return
            node.alive = False
            workers = list(node._workers.values())
            queued = [r for b in node._lease_queue.values() for r in b]
            node._lease_queue.clear()
        from ..exceptions import WorkerCrashedError

        for req in queued:
            if not req.future.done():
                req.future.set_exception(WorkerCrashedError(
                    f"node {node_id.hex()[:8]} disconnected"))
        for w in workers:
            node._on_worker_exit(w)
        # fence the evicted agent: close its channel so a merely-stalled
        # (not dead) agent can't keep executing and report stale results —
        # the agent shuts itself down on head-channel loss
        try:
            node.channel.close()
        except Exception:
            pass
        self.gcs.mark_node_dead(node_id, "agent disconnected")
        self._drop_node_copies(node_id)
        self._reschedule_parked()

    def _drop_node_copies(self, node_id: NodeId) -> None:
        """Node died: purge it from every object's location set."""
        for sh in self._oshards:
            with sh.lock:
                for copies in sh.dir.values():
                    copies.discard(node_id)

    def add_node(self, resources: ResourceSet,
                 labels: Optional[Dict[str, str]] = None) -> Node:
        node = Node(self, NodeId.from_random(), resources, self.session_dir,
                    self.config, labels)
        with self._lock:
            self.nodes[node.node_id] = node
            if getattr(self, "head_node_id", None) is None:
                self.head_node_id = node.node_id
        self.gcs.register_node(node.info())
        self._reschedule_parked()
        self._spill_queued_leases()
        return node

    def remove_node(self, node_id: NodeId, kill: bool = True) -> None:
        with self._lock:
            node = self.nodes.get(node_id)
        if node is None:
            return
        self._count_preempt_outcome(node)
        node.shutdown(kill=kill)
        self.gcs.mark_node_dead(node_id, "removed" if not kill else "killed")
        # objects whose only copies were on this node are now lost
        self._drop_node_copies(node_id)

    def _on_node_state(self, msg) -> None:
        state, node_id = msg[0], msg[1]
        if state == "DEAD":
            self._reschedule_parked()
        elif state == "PREEMPTING":
            # keep the runtime-side drain flag in sync no matter which
            # entrypoint published the notice (autoscaler, chaos, API)
            node = self.nodes.get(node_id)
            if node is not None:
                node.draining = True

    def _views(self) -> List[NodeView]:
        # draining nodes (preemption-noticed) are excluded: no new
        # leases, actors, or placement-group bundles land on a node the
        # provider has promised to kill — in-flight work drains instead
        with self._lock:
            return [
                NodeView(node_id=n.node_id, total=dict(n.total_resources),
                         available=dict(n.available), alive=n.alive,
                         labels=dict(n.labels))
                for n in self.nodes.values()
                if n.alive and not getattr(n, "draining", False)
            ]

    # ---- function export (ref: python/ray/_private/function_manager.py) -----

    def export_function(self, fn: Any) -> str:
        # cache holds the referent so a reused id() can't alias a new function
        key = id(fn)
        cached = self._fn_cache.get(key)
        if cached is not None and cached[0] is fn:
            return cached[1]
        blob = cloudpickle.dumps(fn)
        func_id = hashlib.sha1(blob).hexdigest()
        self.gcs.kv_put("fn:" + func_id, blob, namespace="fn", overwrite=False)
        self._fn_cache[key] = (fn, func_id)
        return func_id

    def get_function_blob(self, func_id: str) -> bytes:
        blob = self.gcs.kv_get("fn:" + func_id, namespace="fn")
        if blob is None:
            raise KeyError(f"function {func_id} not found")
        return blob

    # ---- object API ----------------------------------------------------------

    def _oshard(self, oid: ObjectId) -> _ObjShard:
        return self._oshards[hash(oid) % self._no]

    def object_locations(self, oid: ObjectId) -> Set[NodeId]:
        sh = self._oshard(oid)
        with sh.lock:
            return set(sh.dir.get(oid, ()))

    def add_object_location(self, oid: ObjectId, node_id: NodeId) -> None:
        sh = self._oshard(oid)
        with sh.lock:
            sh.dir.setdefault(oid, set()).add(node_id)

    def object_size_hint(self, oid: ObjectId) -> Optional[int]:
        """Serialized size of a completed object, if the head knows it:
        store-resident objects report the sealed segment size, inline
        results their byte length. None for unknown/in-flight ids — the
        data plane's byte-budget accounting (data/executor.py) treats
        that as 'estimate instead'."""
        sh = self._oshard(oid)
        with sh.lock:
            size = sh.sizes.get(oid)
            if size is not None:
                return int(size)
            data = sh.mem.get(oid)
            return len(data) if data is not None else None

    def object_table_snapshot(self) -> Tuple[Dict[ObjectId, Set[NodeId]],
                                             Set[ObjectId]]:
        """(directory, inline-object ids) merged over the shards — the
        state-API view; not a hot path."""
        directory: Dict[ObjectId, Set[NodeId]] = {}
        inline: Set[ObjectId] = set()
        for sh in self._oshards:
            with sh.lock:
                for oid, nids in sh.dir.items():
                    directory[oid] = set(nids)
                inline.update(sh.mem)
        return directory, inline

    def _event(self, oid: ObjectId) -> threading.Event:
        sh = self._oshard(oid)
        with sh.lock:
            ev = sh.events.get(oid)
            if ev is None:
                ev = sh.events[oid] = threading.Event()
            return ev

    def _notify_object(self, oid: ObjectId) -> None:
        """Object became available: fire its event AND wake any wait()
        callers multi-waiting on it (threading.Event has no select(); the
        waiter list is the event-driven replacement for wait()'s old 2 ms
        polling loop — SURVEY §6's 10k-concurrent-task envelope dies on
        N_waiters × 500 wakeups/s)."""
        self._event(oid).set()
        sh = self._oshard(oid)
        with sh.lock:
            waiters = sh.waiters.pop(oid, None)
        if waiters:
            for w in waiters:
                w.set()

    def _object_available(self, oid: ObjectId) -> bool:
        sh = self._oshard(oid)
        with sh.lock:
            if oid in sh.mem:
                return True
            copies = sh.dir.get(oid) or ()
            return any(
                (n := self.nodes.get(nid)) is not None and n.alive
                for nid in copies)

    def make_ref(self, oid: ObjectId, add_ref: bool = True) -> ObjectRef:
        ref = ObjectRef(oid, owner=self.worker_id)
        if add_ref:
            self.refcount.add_local(oid)
            weakref.finalize(ref, self.refcount.remove_local, oid)
        return ref

    def next_put_id(self, task_id: Optional[TaskId] = None) -> ObjectId:
        with self._lock:
            self._put_counter += 1
            return ObjectId.for_put(task_id or self.driver_task_id, self._put_counter)

    def put(self, value: Any, _owner=None) -> ObjectRef:
        oid = self.next_put_id()
        sobj = serialization.serialize(value)
        self.store_serialized(oid, sobj)
        self.refcount.add_owned(oid)
        return self.make_ref(oid)

    def store_serialized(self, oid: ObjectId, sobj: serialization.SerializedObject,
                         node_id: Optional[NodeId] = None) -> None:
        if sobj.total_bytes <= self.config.max_direct_call_object_size:
            sh = self._oshard(oid)
            with sh.lock:
                sh.mem[oid] = sobj.to_bytes()
        else:
            node = self.nodes.get(node_id) if node_id else None
            if node is None:
                if self.head_node_id is None:
                    raise RuntimeError(
                        "Cannot store a large object: cluster has no nodes yet")
                node = self.nodes[self.head_node_id]
            node.store.put_serialized(oid, sobj, pin=True)
            sh = self._oshard(oid)
            with sh.lock:
                sh.dir.setdefault(oid, set()).add(node.node_id)
                sh.sizes[oid] = sobj.total_bytes
        self._notify_object(oid)

    def store_inline_bytes(self, oid: ObjectId, data: bytes) -> None:
        sh = self._oshard(oid)
        with sh.lock:
            sh.mem[oid] = data
        self._notify_object(oid)

    def on_object_sealed(self, oid: ObjectId, node_id: NodeId,
                         size: Optional[int] = None) -> None:
        sh = self._oshard(oid)
        with sh.lock:
            sh.dir.setdefault(oid, set()).add(node_id)
            if size:
                sh.sizes[oid] = int(size)
        self.refcount.add_owned(oid)
        self._notify_object(oid)

    def _free_object(self, oid: ObjectId) -> None:
        sh = self._oshard(oid)
        with sh.lock:
            sh.mem.pop(oid, None)
            copies = sh.dir.pop(oid, set())
            sh.events.pop(oid, None)
            sh.sizes.pop(oid, None)
            nodes = [self.nodes.get(n) for n in copies]
            nested = sh.nested.pop(oid, [])
        for node in nodes:
            if node is not None:
                node.store.delete(oid)
        self.refcount.forget(oid)
        # the return object dies -> its nested-result borrows unpin
        for inner in nested:
            self.refcount.remove_local(inner)

    def free(self, refs: Sequence[ObjectRef]) -> None:
        for r in refs:
            self._free_object(r.id)

    # fetch: returns ("inline", bytes) or ("shm", name, size)
    # pull-retry backoff (util/retry.py): transient RPC failures against
    # a live holder back off exponentially instead of hammering at a
    # fixed 10ms; the fetch deadline still bounds the whole wait
    _PULL_RETRY = RetryPolicy(initial_backoff_s=0.01, multiplier=1.5,
                              max_backoff_s=0.25, jitter=0.2)

    def fetch_one(self, oid: ObjectId, timeout: Optional[float],
                  on_block=None) -> Tuple:
        deadline = None if timeout is None else time.monotonic() + timeout
        attempts = 0
        transient_attempts = 0
        while True:
            ev = self._event(oid)
            if on_block is not None and not ev.is_set():
                on_block()  # about to actually wait: release caller's lease
                on_block = None
            remaining = None if deadline is None else max(0.0, deadline - time.monotonic())
            if not ev.wait(remaining):
                raise exc.GetTimeoutError(
                    f"Get timed out waiting for object {oid.hex()[:12]}")
            sh = self._oshard(oid)
            with sh.lock:
                data = sh.mem.get(oid)
                copies = list(sh.dir.get(oid, ()))
            if data is not None:
                return ("inline", data)
            transient_failure = False
            for nid in copies:
                node = self.nodes.get(nid)
                if node is not None and node.alive:
                    if getattr(node, "is_remote", False):
                        # chunked pull from the agent, promoted into the
                        # head node's store so later readers are zero-copy.
                        # Concurrent getters share one transfer via the
                        # in-flight pull table (ref: object_manager.h:117
                        # PullManager dedup).
                        try:
                            res = self._pull_once(oid, node)
                        except Exception:
                            # transient RPC failure: the copy may still
                            # exist — retry while the channel stays open
                            if not node.channel.closed:
                                transient_failure = True
                                continue
                            res = None
                        if res is not None:
                            return res
                        # res None = the agent definitively reported the
                        # object gone: fall through to drop the directory
                        # entry so lineage recovery can run
                    else:
                        try:
                            seg = node.store.get_segment(oid)
                        except Exception:
                            # store momentarily full etc. — the copy still
                            # exists
                            transient_failure = True
                            continue
                        if seg is not None:
                            return ("shm", seg[0], seg[1])
                # node dead, or store confirms the object is gone
                with sh.lock:
                    d = sh.dir.get(oid)
                    if d is not None:
                        d.discard(nid)
            if transient_failure:
                # a set availability event makes ev.wait(0) return True,
                # so the deadline must be enforced here too or transient
                # failures past the timeout would retry forever
                if deadline is not None and time.monotonic() > deadline:
                    raise exc.GetTimeoutError(
                        f"Get timed out retrying transient pull "
                        f"failures for object {oid.hex()[:12]}")
                time.sleep(self._PULL_RETRY.backoff(transient_attempts))
                transient_attempts += 1
                continue
            transient_attempts = 0
            # all copies gone -> lineage reconstruction
            attempts += 1
            if attempts > 5:
                raise exc.ObjectLostError(oid.hex())
            self._recover_object(oid)

    def _pull_once(self, oid: ObjectId, node) -> Optional[Tuple]:
        """One chunked transfer per object however many getters: the first
        caller pulls, the rest wait on its Future."""
        sh = self._oshard(oid)
        with sh.lock:
            fut = sh.pulls.get(oid)
            owner = fut is None
            if owner:
                fut = sh.pulls[oid] = Future()
        if not owner:
            # propagate the owner's outcome: None = definitively absent,
            # exception = transient failure (caller retries)
            return fut.result(timeout=300)
        try:
            if _CHAOS is not None and _CHAOS.pull_fail(oid.hex()):
                raise RuntimeError(
                    f"chaos: injected pull failure for {oid.hex()[:12]}")
            data = node.pull_object_bytes(oid)
            res = None if data is None else self._promote_pulled(oid, data)
            fut.set_result(res)
            return res
        except BaseException as e:
            fut.set_exception(e)
            raise
        finally:
            with sh.lock:
                sh.pulls.pop(oid, None)

    def _promote_pulled(self, oid: ObjectId, data: bytes) -> Tuple:
        """Store bytes pulled from a remote node into the head-local store
        and return a fetch result for them."""
        head = self.nodes.get(self.head_node_id)
        if head is not None and head.alive and not getattr(head, "is_remote",
                                                           False):
            try:
                if not head.store.contains(oid):
                    head.store.put_bytes(oid, data, pin=True)
                sh = self._oshard(oid)
                with sh.lock:
                    sh.dir.setdefault(oid, set()).add(head.node_id)
                seg = head.store.get_segment(oid)
                if seg is not None:
                    return ("shm", seg[0], seg[1])
            except Exception:
                pass
        return ("inline", data)

    def _recover_object(self, oid: ObjectId) -> None:
        """Lost-object recovery via lineage re-execution
        (ref: object_recovery_manager.h:41, task_manager.h:234 ResubmitTask)."""
        spec = self.task_manager.lineage_for_object(oid)
        if spec is None:
            raise exc.ObjectLostError(
                oid.hex(), f"Object {oid.hex()[:12]} lost and no lineage available "
                "(put objects and actor-task returns are not reconstructable).")
        if spec.task_type != TaskType.NORMAL_TASK:
            raise exc.ObjectLostError(
                oid.hex(), "Only normal-task outputs can be reconstructed.")
        sh = self._oshard(oid)
        with sh.lock:
            ev = sh.events.get(oid)
            if ev is not None:
                ev.clear()
        with self._lock:
            # single reconstruction per task, however many getters noticed
            if spec.task_id in self._recovering:
                return
            if spec.task_id in {s.task_id for s in self._parked}:
                return
            already = self.task_manager.get(spec.task_id)
            if already is not None and already.state in ("PENDING", "RUNNING"):
                return  # reconstruction already in flight
            self._recovering.add(spec.task_id)
        try:
            self.task_manager.register(spec)
            self._schedule(spec)
        finally:
            with self._lock:
                self._recovering.discard(spec.task_id)

    def deserialize_fetched(self, result: Tuple) -> Any:
        kind = result[0]
        if kind == "inline":
            value = serialization.loads(result[1])
        else:
            _, name, size = result
            mv = self._reader.read(name, size)
            value = serialization.loads(mv)
        if isinstance(value, exc.TaskError):
            cause = value.cause
            if isinstance(cause, exc.RayTpuError):
                raise cause
            raise value
        if isinstance(value, exc.RayTpuError):
            raise value
        return value

    def get(self, refs, timeout: Optional[float] = None):
        single = isinstance(refs, ObjectRef)
        if single:
            refs = [refs]
        t0 = time.perf_counter()
        try:
            out = [self.deserialize_fetched(self.fetch_one(r.id, timeout))
                   for r in refs]
        finally:
            _H_GET_WAIT.observe(time.perf_counter() - t0)
        return out[0] if single else out

    def get_many(self, oids: List[ObjectId], timeout: Optional[float] = None):
        return [self.deserialize_fetched(self.fetch_one(o, timeout)) for o in oids]

    def get_async(self, ref: ObjectRef):
        import asyncio

        loop = asyncio.get_event_loop()
        return loop.run_in_executor(self._pool, lambda: self.get(ref))

    def as_future(self, ref: ObjectRef) -> Future:
        return self._pool.submit(self.get, ref)

    def wait(self, refs: List[ObjectRef], num_returns: int = 1,
             timeout: Optional[float] = None,
             fetch_local: bool = True, on_block=None
             ) -> Tuple[List[ObjectRef], List[ObjectRef]]:
        if num_returns > len(refs):
            raise ValueError("num_returns > len(refs)")
        deadline = None if timeout is None else time.monotonic() + timeout
        pending = list(refs)
        ready: List[ObjectRef] = []
        while True:
            for r in list(pending):
                if len(ready) >= num_returns:
                    break  # contract: ready has AT MOST num_returns
                    # entries (ref: ray.wait docs) — extras stay pending
                if self._event(r.id).is_set():
                    ready.append(r)
                    pending.remove(r)
            if len(ready) >= num_returns or not pending:
                break
            if deadline is not None and time.monotonic() >= deadline:
                break
            # event-driven sleep: one waiter event registered on every
            # pending object; _notify_object wakes us on the first arrival
            # (no polling — the old 2 ms loop burned a core per waiter)
            wake = threading.Event()
            registered: List[ObjectId] = []
            fired = False
            for r in pending:
                sh = self._oshard(r.id)
                with sh.lock:
                    # per-oid atomicity is what matters: the event-set
                    # check and waiter registration can't race THIS oid's
                    # _notify_object
                    ev = sh.events.get(r.id)
                    if ev is not None and ev.is_set():
                        fired = True  # raced a completion: re-scan now
                        break
                    sh.waiters.setdefault(r.id, []).append(wake)
                    registered.append(r.id)
            if not fired:
                if on_block is not None:
                    on_block()
                    on_block = None
                remaining = (None if deadline is None
                             else max(0.0, deadline - time.monotonic()))
                wake.wait(remaining)
            for oid in registered:
                sh = self._oshard(oid)
                with sh.lock:
                    ws = sh.waiters.get(oid)
                    if ws is not None:
                        try:
                            ws.remove(wake)
                        except ValueError:
                            pass
                        if not ws:
                            sh.waiters.pop(oid, None)
        return ready, pending

    # ---- task submission -----------------------------------------------------

    def new_task_id(self) -> TaskId:
        return TaskId.from_random()

    def prepare_runtime_env(self, renv: Optional[dict]) -> Optional[dict]:
        """Merge with the job-level default, zip+upload local dirs into the
        GCS KV, and stamp the dedication hash — once per distinct env
        (content-addressed cache). ref: runtime_env_agent.py:161, here run
        submitter-side because the KV is the package store."""
        from . import runtime_env as renv_mod

        merged = renv_mod.merge(self.default_runtime_env,
                                renv_mod.validate(renv))
        if not merged:
            return None
        key = renv_mod.cache_key(merged)
        cached = self._renv_cache.get(key)
        if cached is None:
            cached = renv_mod.package(
                merged,
                lambda k, b: self.gcs.kv_put(
                    k, b, namespace=renv_mod.KV_NAMESPACE, overwrite=False))
            self._renv_cache[key] = cached
        return cached

    def submit_spec(self, spec: TaskSpec, _count: bool = True) -> List[ObjectRef]:
        if spec.task_type == TaskType.ACTOR_TASK and self._direct_enabled:
            refs = self._submit_actor_direct(spec)
            if refs is not None:
                return refs
        if _count:
            _C_ROUTED.inc()
            _rec_dispatch("routed", spec)
        self.task_manager.register(spec)
        # SUBMITTED opens the lifecycle phase chain (-> SCHEDULED ->
        # RUNNING -> FINISHED); the GCS derives phase histograms from it
        ev = {"task_id": spec.task_id.hex(), "name": spec.description,
              "state": "SUBMITTED", "time": time.time()}
        if spec.actor_id is not None:
            ev["actor_id"] = spec.actor_id.hex()
        self.gcs.add_task_event(ev)
        for ref in spec.arg_refs():
            self.refcount.pin_for_task(ref.id)
        for oid in spec.return_ids():
            self.refcount.add_owned(oid)
        refs = [self.make_ref(oid) for oid in spec.return_ids()]
        if spec.task_type == TaskType.ACTOR_TASK:
            self._submit_actor_spec(spec)
        else:
            self._schedule(spec)
        return refs

    def _schedule(self, spec: TaskSpec) -> None:
        strat = spec.scheduling_strategy
        demand = spec.__dict__.get("_demand")
        if demand is None:
            demand = normalize(spec.resources)
        node: Optional[Node] = None
        if strat.kind == "PLACEMENT_GROUP" and strat.placement_group_id is not None:
            pg = self.gcs.get_pg(strat.placement_group_id)
            if pg is None or pg.state == "REMOVED":
                self._fail_task(spec, exc.PlacementGroupUnschedulableError(
                    "placement group removed"))
                return
            if pg.state != "CREATED":
                with self._lock:
                    self._parked.append(spec)
                # the placer may have committed (or a remove landed)
                # between the state read and the append — its
                # _reschedule_parked_tasks would then have missed this
                # spec; re-check so no task parks forever
                if pg.state in ("CREATED", "REMOVED"):
                    self._reschedule_parked_tasks()
                return
            candidates = (
                [pg.bundle_nodes[strat.bundle_index]]
                if strat.bundle_index >= 0 else list(dict.fromkeys(pg.bundle_nodes))
            )
            for nid in candidates:
                n = self.nodes.get(nid)
                if n is not None and n.alive:
                    node = n
                    break
        elif strat.kind == "DEFAULT" and len(self.nodes) == 1:
            # single-node fast path: locality and hybrid scoring are
            # cross-node concerns; the only question is feasibility
            # (infeasible demand still parks, same as pick_node=None)
            n = next(iter(self.nodes.values()))
            node = n if (n.alive and not getattr(n, "draining", False)
                         and res_ge(n.total_resources, demand)) \
                else None
        else:
            if strat.kind == "NODE_AFFINITY" and not strat.soft:
                target = self.nodes.get(strat.node_id)
                if target is None or not target.alive:
                    self._fail_task(spec, exc.RayTpuError(
                        f"Task {spec.description}: hard node affinity to "
                        f"dead/unknown node {strat.node_id.hex()[:8]}"))
                    return
            nid = self.scheduler.pick_node(self._views(), demand, strat,
                                           local_node_id=self.head_node_id,
                                           locality=self._arg_locality(spec))
            node = self.nodes.get(nid) if nid is not None else None
        if node is None:
            with self._lock:
                self._parked.append(spec)
            return
        self.gcs.add_task_event({
            "task_id": spec.task_id.hex(), "name": spec.description,
            "state": "SCHEDULED", "node_id": node.node_id.hex(),
            "time": time.time()})
        if _FLREC.enabled:
            _FLREC.record("sched.place", spec.description,
                          {"task": spec.task_id.hex()[:12],
                           "node": node.node_id.hex()[:12],
                           "strategy": strat.kind})
        self.task_manager.mark_running(spec.task_id)
        fut = node.request_lease(spec)

        def _granted(f: Future, node=node):
            try:
                worker = f.result()
            except Exception as e:
                # the lease error (e.g. container launcher failure) rides
                # into the final retries-exhausted message
                self.on_worker_crashed(spec, node.node_id, reason=str(e))
                return
            self._event_running(spec, node.node_id)
            node.push_task(worker, spec)

        fut.add_done_callback(_granted)

    def _arg_locality(self, spec: TaskSpec) -> Dict[NodeId, int]:
        """Bytes of the task's arguments resident per node (the input to
        the locality-aware lease policy; ref: lease_policy.cc:22 builds
        the same map from the ownership/locality data). Inline args are
        location-free and contribute nothing."""
        weights: Dict[NodeId, int] = {}
        for ref in spec.arg_refs():
            oid = ref.id
            sh = self._oshard(oid)
            with sh.lock:
                nodes = list(sh.dir.get(oid) or ())
                # real sealed sizes tracked at seal/put time; unknown
                # sizes weigh 1 MiB (big enough to beat emptiness, small
                # enough not to drown real size info)
                size = sh.sizes.get(oid) or (1 << 20)
            for nid in nodes:
                weights[nid] = weights.get(nid, 0) + size
        return weights

    def _reschedule_parked_tasks(self) -> None:
        with self._lock:
            parked, self._parked = self._parked, []
        for spec in parked:
            try:
                self._schedule(spec)
            except Exception as e:
                # one bad spec (e.g. a node channel dying mid-lease) must
                # not drop the rest of the swapped-out parked list
                try:
                    self._fail_task(spec, exc.RayTpuError(
                        f"reschedule failed: {e!r}"))
                except Exception:
                    pass

    def _reschedule_parked(self) -> None:
        self._reschedule_parked_tasks()
        # cluster membership/capacity changed: parked pending PGs get
        # another placement pass through the single placer thread
        self._wake_pg_placer(recheck_parked=True)

    def _spill_queued_leases(self, node=None,
                             everything: bool = False) -> int:
        """Lease spillback (the reference's raylet spillback, reduced):
        queued-but-ungranted lease requests move back through the
        scheduler when the cluster's shape changed under them — a new
        node joined (a request stuck behind a full node can run there
        NOW), or ``node`` started draining (``everything=True``: nothing
        new may start there). Without this, a request queued on a
        busy-but-feasible node waits for THAT node forever and fresh
        autoscaler capacity goes unused."""
        victims = [node] if node is not None else [
            n for n in list(self.nodes.values())
            if n.alive and not getattr(n, "draining", False)]
        moved = 0
        for n in victims:
            try:
                stolen = n.steal_queued_leases(everything=everything)
            except Exception:
                continue
            for req in stolen:
                moved += 1
                try:
                    self._schedule(req.spec)
                except Exception as e:
                    try:
                        self._fail_task(req.spec, exc.RayTpuError(
                            f"lease spillback failed: {e!r}"))
                    except Exception:
                        pass
        return moved

    # ---- streaming generators (ref: core_worker.proto:436) -------------------

    def _gen_state(self, task_id: TaskId) -> dict:
        with self._lock:
            g = self._generators.get(task_id)
            if g is None:
                g = self._generators[task_id] = {
                    "items": {}, "done": False, "error": None,
                    "event": threading.Event()}
            return g

    def on_generator_item(self, task_id: TaskId, index: int, oid: ObjectId,
                          data: Optional[bytes] = None) -> bool:
        """A worker reported one yielded item (inline bytes, or already
        sealed into a store). Returns False when the consumer dropped the
        generator — the worker stops producing (the cancellation half of
        the streaming protocol)."""
        if data is not None:
            self.store_inline_bytes(oid, data)
        # Tombstone check, item insertion, AND the ownership count must share
        # one lock acquisition: a release interleaved between them would
        # either resurrect the popped generator dict or free-check the item
        # before it is owned, leaking it permanently. (_lock is an RLock, so
        # the nested _gen_state/add_owned calls are safe.)
        with self._lock:
            released = task_id in self._released_generators
            if not released:
                g = self._gen_state(task_id)
                g["items"][index] = oid
                self.refcount.add_owned(oid)
        if released:
            self._free_object(oid)
            return False
        g["event"].set()
        return True

    def _generator_finish(self, task_id: TaskId,
                          error: Optional[bytes] = None) -> None:
        with self._lock:
            if task_id in self._released_generators:
                # stream ended after the consumer dropped it: tombstone done
                self._released_generators.discard(task_id)
                return
        g = self._gen_state(task_id)
        with self._lock:
            g["done"] = True
            if error is not None:
                g["error"] = error
        g["event"].set()

    def next_generator_item(self, task_id: TaskId, index: int,
                            timeout: Optional[float] = None,
                            on_block=None) -> Optional[ObjectRef]:
        """Blocks until item `index` exists; None = generator exhausted."""
        g = self._gen_state(task_id)
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            with self._lock:
                oid = g["items"].get(index)
                if oid is not None:
                    return self.make_ref(oid)
                if g["error"] is not None:
                    err = serialization.loads(g["error"])
                    raise err if isinstance(err, BaseException) \
                        else exc.TaskError(cause=RuntimeError(str(err)))
                if g["done"]:
                    return None
                g["event"].clear()
            if on_block is not None:
                on_block()
                on_block = None
            remaining = (None if deadline is None
                         else max(0.0, deadline - time.monotonic()))
            if not g["event"].wait(remaining):
                raise exc.GetTimeoutError(
                    f"generator item {index} of {task_id.hex()[:12]}")

    def release_generator(self, task_id: TaskId) -> None:
        """Generator GC'd: free yielded items nothing ever referenced and
        tombstone the task so late items are rejected (which tells the
        producing worker to stop)."""
        with self._lock:
            g = self._generators.pop(task_id, None)
            spec = self.task_manager.get(task_id)
            if spec is not None and spec.state in ("PENDING", "RUNNING"):
                self._released_generators.add(task_id)
        if g is None:
            return
        for oid in g["items"].values():
            # atomic check-and-free through the refcounter (a zero-count
            # decrement frees only when truly unreferenced)
            self.refcount.remove_local(oid, 0)

    def _event_running(self, spec: TaskSpec, node_id: NodeId) -> None:
        """Start-of-execution event: pairs with the FINISHED/FAILED event
        to give the timeline durations (ref: task_event_buffer.h:199 state
        transitions feeding GcsTaskManager)."""
        ev = {"task_id": spec.task_id.hex(), "name": spec.description,
              "state": "RUNNING", "node_id": node_id.hex(),
              "time": time.time()}
        if spec.actor_id is not None:
            ev["actor_id"] = spec.actor_id.hex()  # drill-down join key
        self.gcs.add_task_event(ev)

    def _fail_task(self, spec: TaskSpec, error: Exception) -> None:
        self.task_manager.fail(spec.task_id)
        blob = serialization.dumps(error)
        for oid in spec.return_ids():
            # results sealed before the failure was noticed stay valid (the
            # task_done message races the store seal on deliberate kills)
            if not self._object_available(oid):
                self.store_inline_bytes(oid, blob)
        if spec.num_returns == STREAMING_RETURNS:
            self._generator_finish(spec.task_id, error=blob)
        for ref in spec.arg_refs():
            self.refcount.unpin_for_task(ref.id)
        self.gcs.add_task_event({"task_id": spec.task_id.hex(), "name": spec.description,
                                 "state": "FAILED", "time": time.time()})

    # called by Node when a worker reports a finished task
    def on_task_done(self, spec: TaskSpec, payload: dict, node_id: NodeId,
                     worker: WorkerHandle) -> None:
        error = payload.get("error")
        if error is not None:
            # streaming tasks never retry transparently: a rerun would
            # re-mint the same item ids under refs already consumed
            if spec.retry_exceptions \
                    and spec.num_returns != STREAMING_RETURNS:
                retry = self.task_manager.try_retry(spec.task_id)
                if retry is not None:
                    self._schedule(retry)
                    return
            self.task_manager.fail(spec.task_id)
            for oid in spec.return_ids():
                self.store_inline_bytes(oid, error)
            if spec.num_returns == STREAMING_RETURNS:
                self._generator_finish(spec.task_id, error=error)
            if spec.task_type == TaskType.ACTOR_CREATION_TASK:
                self._on_actor_creation_failed(spec, node_id, worker)
        else:
            results = payload.get("results") or []
            borrowed = payload.get("borrowed") or []
            if borrowed and spec.num_returns > 0:
                # refs nested inside EACH return value borrow through
                # THAT return object: pin them for its lifetime so the
                # producing worker dropping its own ref (function exit)
                # can't free them before the caller deserializes
                # (borrower protocol; ref: reference_count.h:61
                # nested-ref ownership). `borrowed` aligns with
                # return_ids; a legacy flat list pins through ret 0.
                rids = spec.return_ids()
                if borrowed and not isinstance(borrowed[0], list):
                    borrowed = [list(borrowed)]
                for rid, nested in zip(rids, borrowed):
                    if nested:
                        sh = self._oshard(rid)
                        with sh.lock:
                            sh.nested.setdefault(rid, []).extend(nested)
                for nested in borrowed:
                    for oid in nested:
                        self.refcount.add_local(oid)
            t_put = time.perf_counter()
            for oid, res in zip(spec.return_ids(), results):
                if res[0] == "inline":
                    self.store_inline_bytes(oid, res[1])
                # "stored" results were registered at seal time
            if results:
                _H_RESULT_PUT.observe(time.perf_counter() - t_put)
            if spec.num_returns == STREAMING_RETURNS:
                self._generator_finish(spec.task_id)
            self.task_manager.complete(spec.task_id)
            if spec.task_type == TaskType.ACTOR_CREATION_TASK:
                self._on_actor_created(spec, node_id, worker)
        for ref in spec.arg_refs():
            self.refcount.unpin_for_task(ref.id)
        ev = {"task_id": spec.task_id.hex(), "name": spec.description,
              "state": "FAILED" if error is not None else "FINISHED",
              "node_id": node_id.hex(), "time": time.time()}
        if spec.actor_id is not None:
            ev["actor_id"] = spec.actor_id.hex()
        self.gcs.add_task_event(ev)

    def on_worker_crashed(self, spec: TaskSpec, node_id: NodeId,
                          reason: str = "") -> None:
        if spec.task_type == TaskType.ACTOR_CREATION_TASK:
            return  # actor FSM handles restart / death
        if spec.num_returns == STREAMING_RETURNS:
            # no transparent re-run: items already delivered would repeat
            self._fail_task(spec, exc.WorkerCrashedError(
                f"Worker died while streaming {spec.description}"))
            return
        if spec.num_returns > 0 and all(
                self._object_available(oid) for oid in spec.return_ids()):
            # results were sealed (on a live node) before the crash: the task
            # finished, only its task_done message was lost
            self.task_manager.complete(spec.task_id)
            for ref in spec.arg_refs():
                self.refcount.unpin_for_task(ref.id)
            return
        if spec.task_type == TaskType.ACTOR_TASK:
            rec = self._actors.get(spec.actor_id)
            info = self.gcs.get_actor(spec.actor_id)
            if rec is not None and info is not None \
                    and info.state != ActorState.DEAD:
                # single retry budget: TaskManager's retries_left (registered
                # from max_task_retries) — not a second in-spec counter
                retry = self.task_manager.try_retry(spec.task_id)
                if retry is not None:
                    with rec.lock:
                        rec.queued.insert(0, retry)
                    return
            err = exc.ActorDiedError(
                f"Actor {spec.actor_id.hex()[:8]} died while running "
                f"{spec.description}")
            self._fail_task(spec, err)
            return
        retry = self.task_manager.try_retry(spec.task_id)
        if retry is not None:
            self._schedule(retry)
            return
        detail = f": {reason}" if reason else ""
        self._fail_task(spec, exc.WorkerCrashedError(
            f"Worker died while running {spec.description} "
            f"(node {node_id.hex()[:8]}); retries exhausted{detail}"))

    # ---- actors --------------------------------------------------------------

    def create_actor(self, spec: TaskSpec, name: str = "", detached: bool = False,
                     meta: Optional[dict] = None) -> None:
        info = ActorInfo(
            actor_id=spec.actor_id, name=name, namespace=self.namespace,
            job_id=self.job_id, state=ActorState.PENDING_CREATION,
            creation_spec=spec, max_restarts=spec.max_restarts, detached=detached)
        self.gcs.register_actor(info)
        if meta is not None:
            self.gcs.kv_put("actor_meta:" + spec.actor_id.hex(),
                            cloudpickle.dumps(meta), namespace="actor")
        with self._lock:
            self._actors[spec.actor_id] = _ActorRecord(info=info)
        self.submit_spec(spec)

    def _on_actor_created(self, spec: TaskSpec, node_id: NodeId,
                          worker: WorkerHandle) -> None:
        rec = self._actors.get(spec.actor_id)
        info = self.gcs.get_actor(spec.actor_id)
        if info is not None and info.state == ActorState.DEAD:
            # killed while the creation task was in flight — don't resurrect
            node = self.nodes.get(node_id)
            if node is not None:
                node.kill_worker(worker, force=True)
            return
        if rec is None:
            return
        with rec.lock:
            rec.worker = worker
            rec.node_id = node_id
            rec.seq = 0  # fresh worker instance expects sequence from 0;
            # must happen BEFORE ALIVE is visible so no direct submission can
            # grab a sequence number that the flush below will reuse
            # new placement epoch: direct callers' cached lanes are keyed
            # by it (a restarted actor's fresh ActorQueue expects every
            # lane from 0) and the peer channel must be re-established
            rec.epoch += 1
            rec.dseq = 0
            rec.dlane = 0  # fresh ActorQueue: lane numbering starts over
            rec.direct_chan = None
            rec.direct_bad = 0.0
        self.gcs.set_actor_state(spec.actor_id, ActorState.ALIVE,
                                 node_id=node_id, worker_id=worker.worker_id)
        self._flush_actor_queue(spec.actor_id)

    def _on_actor_creation_failed(self, spec: TaskSpec, node_id: NodeId,
                                  worker: WorkerHandle) -> None:
        self.gcs.set_actor_state(spec.actor_id, ActorState.DEAD,
                                 death_cause="creation task failed")
        self._drain_actor_queue_with_error(spec.actor_id,
                                           "actor creation failed")
        # the dedicated worker holds a lease; tear it down so resources return
        node = self.nodes.get(node_id)
        if node is not None:
            node.release_lease(worker, terminate=True)

    def _restart_actor(self, info: ActorInfo) -> None:
        """GCS FSM asked for a restart: resubmit the creation task."""
        import copy

        spec = copy.copy(info.creation_spec)
        spec.task_id = self.new_task_id()
        rec = self._actors.get(info.actor_id)
        if rec is not None:
            with rec.lock:
                rec.worker = None
        self.task_manager.register(spec)
        self._schedule(spec)

    def _on_actor_state(self, msg) -> None:
        actor_id, state = msg
        if state == ActorState.DEAD:
            # direct in-flights first: their routed resubmission hits the
            # DEAD record and surfaces the typed ActorDiedError
            self._recover_direct_inflight(actor_id)
            self._drain_actor_queue_with_error(actor_id, "actor is dead")
        elif state == ActorState.RESTARTING:
            # re-queue un-answered direct calls through the head; they run
            # on the new incarnation in head-lane order
            self._recover_direct_inflight(actor_id)

    def _submit_actor_spec(self, spec: TaskSpec) -> None:
        rec = self._actors.get(spec.actor_id)
        if rec is None:
            self._fail_task(spec, exc.ActorDiedError(
                f"Actor {spec.actor_id.hex()[:8]}: unknown actor"))
            return
        with rec.lock:
            # state read and enqueue are atomic w.r.t. _on_actor_created's
            # seq reset + flush, so no submission can straddle a restart
            info = self.gcs.get_actor(spec.actor_id)
            if info is None or info.state == ActorState.DEAD:
                cause = info.death_cause if info else "unknown actor"
                dead_cause = cause
            elif info.state == ActorState.ALIVE and rec.worker is not None \
                    and not rec.queued:
                # direct path only when no earlier tasks are still queued —
                # otherwise this call would overtake them in sequence order
                spec.seq_no = rec.seq
                rec.seq += 1
                node = self.nodes.get(rec.node_id)
                worker = rec.worker
                dead_cause = None
            else:
                rec.queued.append(spec)
                return
        if dead_cause is not None:
            self._fail_task(spec, exc.ActorDiedError(
                f"Actor {spec.actor_id.hex()[:8]} is dead: {dead_cause}"))
            return
        if node is None or not node.alive:
            # same node-death window as in _flush_actor_queue: park, don't
            # burn a retry — the actor FSM decides restart vs DEAD.
            restarted = False
            with rec.lock:
                if rec.worker is worker:
                    rec.seq -= 1
                    rec.queued.insert(0, spec)
                    rec.worker = None
                else:
                    # restart completed in the window: rec.seq/worker belong
                    # to the new epoch — don't clobber them, requeue for a
                    # fresh seq assignment on the new worker
                    rec.queued.insert(0, spec)
                    restarted = rec.worker is not None
            if restarted:
                self._flush_actor_queue(spec.actor_id)
            return
        self._event_running(spec, node.node_id)
        node.push_task(worker, spec)

    def _flush_actor_queue(self, actor_id: ActorId) -> None:
        rec = self._actors.get(actor_id)
        if rec is None:
            return
        # drain one at a time, assigning sequence numbers under the lock, so
        # concurrent direct submissions (which defer while the queue is
        # non-empty) can never overtake queued tasks
        while True:
            with rec.lock:
                info = self.gcs.get_actor(actor_id)
                if info is None or info.state != ActorState.ALIVE \
                        or rec.worker is None or not rec.queued:
                    break
                spec = rec.queued.pop(0)
                spec.seq_no = rec.seq
                rec.seq += 1
                node = self.nodes.get(rec.node_id)
                worker = rec.worker
            if node is None or not node.alive:
                # node-death window (node dead, actor FSM not yet notified):
                # park the task and stop — no retry consumed, no busy-spin.
                # The restart (or DEAD transition) re-drives this queue.
                with rec.lock:
                    if rec.worker is worker:
                        rec.seq -= 1
                        rec.queued.insert(0, spec)
                        rec.worker = None
                        break
                    # restart won the race — requeue and retry on the new
                    # worker epoch (loop re-pops with a fresh seq)
                    rec.queued.insert(0, spec)
                continue
            self._event_running(spec, node.node_id)
            node.push_task(worker, spec)
        # a task may have been appended after the final lock release — if the
        # queue is non-empty and the actor is alive, a new flush is required
        with rec.lock:
            again = bool(rec.queued) and rec.worker is not None
        if again:
            info = self.gcs.get_actor(actor_id)
            if info is not None and info.state == ActorState.ALIVE:
                self._flush_actor_queue(actor_id)

    def _drain_actor_queue_with_error(self, actor_id: ActorId, cause: str) -> None:
        rec = self._actors.get(actor_id)
        if rec is None:
            return
        with rec.lock:
            queued, rec.queued = rec.queued, []
        for spec in queued:
            self._fail_task(spec, exc.ActorDiedError(
                f"Actor {actor_id.hex()[:8]}: {cause}"))

    # ---- direct dispatch (docs/DISPATCH.md) ----------------------------------
    #
    # Steady-state actor calls bypass the routed machinery: once the actor
    # is ALIVE with no queued backlog, the driver numbers the call in its
    # own lane (owner_id = driver worker id) and ships it straight to the
    # owning worker — over the worker's own channel (local nodes: that
    # channel already connects this process to the worker process) or a
    # cached peer connection to the worker's direct socket (remote nodes).
    # No task_manager entry, no per-call GCS events, no lease traffic; the
    # worker replies with a direct_result frame and batches lifecycle
    # events separately. Fallback on any failure is resubmission through
    # the routed path, which owns the actor FSM / retry / typed-error
    # semantics.

    @staticmethod
    def _direct_eligible(spec: TaskSpec) -> bool:
        if spec.num_returns == STREAMING_RETURNS:
            return False
        # ref args would make the executing worker fetch through the head
        # anyway, and need submit-time pinning the direct path skips
        for a in spec.args:
            if a[0] == ARG_REF:
                return False
        for a in spec.kwargs.values():
            if a[0] == ARG_REF:
                return False
        return True

    def _submit_actor_direct(self, spec: TaskSpec) -> Optional[List[ObjectRef]]:
        if not self._direct_eligible(spec):
            return None
        rec = self._actors.get(spec.actor_id)
        if rec is None:
            return None
        new_chan = None
        with rec.lock:
            if rec.worker is None or rec.queued:
                return None
            info = self.gcs.get_actor(spec.actor_id)
            if info is None or info.state != ActorState.ALIVE:
                return None
            node = self.nodes.get(rec.node_id)
            if node is None or not node.alive:
                return None
            worker = rec.worker
            if not getattr(node, "is_remote", False):
                chan = worker.channel
                if chan is None or chan.closed:
                    return None
            else:
                chan = rec.direct_chan
                if chan is None or chan.closed:
                    if rec.direct_bad > time.monotonic() \
                            or not worker.direct_addr:
                        return None
                    from .rpc import connect as _rpc_connect

                    try:
                        # same-host agents expose the worker's unix socket;
                        # an unreachable path (true cross-host, or a
                        # transiently refused connect) stays routed for the
                        # negative-cache window, then retries
                        chan = _rpc_connect(worker.direct_addr,
                                            handler=self._direct_peer_handler,
                                            name="dpeer")
                    except Exception:
                        # dispatch-fallback backoff (util/retry.py): the
                        # routed window grows with consecutive failures
                        rec.direct_bad = time.monotonic() + \
                            _DIRECT_RECONNECT.backoff(rec.direct_fails)
                        rec.direct_fails += 1
                        return None
                    rec.direct_fails = 0
                    new_chan = chan
                    rec.direct_chan = chan
                    # new connection era: seq numbering restarts with it
                    # (frames lost in the old socket would otherwise leave
                    # the worker lane's expected counter behind forever)
                    rec.dlane += 1
                    rec.dseq = 0
            spec.owner_id = self.worker_id
            spec.seq_no = rec.dseq
            rec.dseq += 1
            # gate: the worker runs this lane only after dispatching every
            # head-routed task numbered below rec.seq — my earlier routed
            # calls all are, so per-caller FIFO survives the transition
            gate = rec.seq
            era = rec.dlane
            rec.direct_inflight[spec.task_id] = spec
        if new_chan is not None:
            # registered only after rec.lock is dropped: on_close fires the
            # callback synchronously when the channel already died, and
            # _on_direct_peer_close re-takes this record's non-reentrant
            # lock — registering under it would self-deadlock. A close in
            # the unregistered window is caught by the chan.closed check
            # below (recovery is idempotent).
            new_chan.on_close(
                lambda aid=spec.actor_id, ch=new_chan:
                self._on_direct_peer_close(aid, ch))
        for oid in spec.return_ids():
            self.refcount.add_owned(oid)
        refs = [self.make_ref(oid) for oid in spec.return_ids()]
        chan.notify("direct_submit", {"spec": spec, "gate": gate,
                                      "lane": era})
        _C_DIRECT.inc()
        _rec_dispatch("direct", spec)
        if chan.closed:
            # raced the worker's death: the notify may be lost — recover
            # now (idempotent; results that did land are respected)
            self._recover_direct_inflight(spec.actor_id)
        return refs

    def _direct_peer_handler(self, method: str, payload):
        if method == "direct_result":
            self.on_direct_result(payload)
            return None
        raise ValueError(f"unknown direct peer message {method}")

    def _on_direct_peer_close(self, actor_id: ActorId, chan=None) -> None:
        rec = self._actors.get(actor_id)
        if rec is None:
            return
        with rec.lock:
            # a late close callback must not clobber a channel that was
            # already re-established; recovery still runs (idempotent —
            # results that landed are respected, the rest resubmit routed)
            if chan is None or rec.direct_chan is chan:
                rec.direct_chan = None
        self._recover_direct_inflight(actor_id)

    def on_direct_result(self, payload: dict) -> None:
        """A worker finished one of this driver's direct calls: results
        land straight in the driver's store — no refcount pins, no
        task_manager entry to retire, no per-call GCS event."""
        rec = self._actors.get(payload.get("actor_id"))
        if rec is None:
            return
        with rec.lock:
            spec = rec.direct_inflight.pop(payload["task_id"], None)
        if spec is None:
            return
        if payload.get("stale"):
            # the socket now belongs to a process not hosting this actor:
            # drop the cache and re-route through the head (the next
            # placement epoch resets the deadline early)
            with rec.lock:
                rec.direct_bad = time.monotonic() + \
                    _DIRECT_RECONNECT.backoff(rec.direct_fails)
                rec.direct_fails += 1
                rec.direct_chan = None
            self._resubmit_direct(spec)
            return
        error = payload.get("error")
        if error is not None:
            for oid in spec.return_ids():
                self.store_inline_bytes(oid, error)
            return
        for oid, res in zip(spec.return_ids(), payload.get("results") or []):
            if res[0] == "inline":
                self.store_inline_bytes(oid, res[1])
            # ("stored", None): sealed into a store / shipped via
            # direct_result_stored — registered at seal time

    def _recover_direct_inflight(self, actor_id: ActorId) -> None:
        """Peer/worker failure or actor restart: every un-answered direct
        call re-enters the routed path, which applies the actor FSM's
        semantics (queue for restart, or typed ActorDiedError)."""
        rec = self._actors.get(actor_id)
        if rec is None:
            return
        with rec.lock:
            inflight = sorted(rec.direct_inflight.values(),
                              key=lambda s: s.seq_no)
            rec.direct_inflight.clear()
        for spec in inflight:
            self._resubmit_direct(spec)

    def _resubmit_direct(self, spec: TaskSpec) -> None:
        import copy

        if spec.num_returns > 0 and all(
                self._object_available(oid) for oid in spec.return_ids()):
            return  # the result landed before the failure was noticed
        # Routed-path retry semantics: a direct task in flight when its
        # worker died is "crashed while running" — it re-runs only with a
        # retry budget (max_task_retries), else fails typed. Re-running
        # unconditionally would replay a crash-causing call into the
        # restarted incarnation and burn its restart budget. If the actor
        # is still ALIVE (a dropped peer connection, not a death), the
        # call may simply have been lost — resubmit regardless.
        info = self.gcs.get_actor(spec.actor_id)
        alive = info is not None and info.state == ActorState.ALIVE
        if not alive and spec.max_retries == 0:
            self._fail_task(spec, exc.ActorDiedError(
                f"Actor {spec.actor_id.hex()[:8]} died while running "
                f"{spec.description}"))
            return
        # copy before mutating: the original direct frame may still sit in
        # an outbox, and a late encode must not see head-lane fields
        spec = copy.copy(spec)
        spec.owner_id = None  # back to the head-routed lane
        spec.seq_no = 0
        _C_ROUTED.inc()
        _rec_dispatch("routed", spec)
        self.task_manager.register(spec)
        self._submit_actor_spec(spec)

    def resolve_actor(self, actor_id: ActorId) -> Optional[dict]:
        """Placement lookup for a direct caller (worker): returns the
        owning worker's direct address + the epoch stamp callers key
        their lane state by + the head-lane gate. None = not directly
        reachable right now (not ALIVE, queued backlog, or no direct
        socket) — the caller stays routed and may re-resolve later."""
        if not self._direct_enabled:
            return None
        rec = self._actors.get(actor_id)
        if rec is None:
            return None
        with rec.lock:
            info = self.gcs.get_actor(actor_id)
            if info is None or info.state != ActorState.ALIVE:
                return None
            if rec.worker is None or rec.queued:
                return None
            addr = rec.worker.direct_addr
            if not addr:
                return None
            return {"addr": addr, "worker_id": rec.worker.worker_id,
                    "node_id": rec.node_id, "epoch": rec.epoch,
                    "gate": rec.seq}

    def ensure_published(self, oid: ObjectId) -> None:
        """Driver direct results land in the store at arrival — nothing
        to publish (the WorkerRuntime override is the real one)."""

    def dispatch_stats(self) -> dict:
        d, r = dispatch_counts()
        return {"direct": d, "routed": r}

    def kill_actor(self, actor_id: ActorId, no_restart: bool = True) -> None:
        info = self.gcs.get_actor(actor_id)
        if info is None:
            return
        if no_restart:
            info.max_restarts = 0
        rec = self._actors.get(actor_id)
        worker = rec.worker if rec else None
        node = self.nodes.get(rec.node_id) if rec and rec.node_id else None
        if worker is not None and node is not None:
            node.kill_worker(worker, force=True)
        else:
            self.gcs.on_actor_failure(actor_id, "killed via ray_tpu.kill")

    def actor_state(self, actor_id: ActorId) -> str:
        info = self.gcs.get_actor(actor_id)
        return info.state.name if info else "UNKNOWN"

    def wait_for_actor(self, actor_id: ActorId, timeout: float = 60.0) -> None:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            info = self.gcs.get_actor(actor_id)
            if info is not None and info.state == ActorState.ALIVE:
                return
            if info is not None and info.state == ActorState.DEAD:
                raise exc.ActorDiedError(info.death_cause)
            time.sleep(0.01)
        raise exc.GetTimeoutError(f"actor {actor_id.hex()[:8]} not alive in time")

    # ---- placement groups (ref: gcs_placement_group_manager.cc 2PC) ----------

    def create_placement_group(self, bundles: List[ResourceSet], strategy: str,
                               name: str = "") -> PlacementGroupId:
        from .gcs import PlacementGroupInfo

        pg_id = PlacementGroupId.from_random()
        info = PlacementGroupInfo(pg_id=pg_id, bundles=[normalize(b) for b in bundles],
                                  strategy=strategy, name=name)
        self.gcs.register_pg(info)
        with self._pg_cv:
            self._pg_pending.append(pg_id)
            self._pg_cv.notify()
        return pg_id

    def _wake_pg_placer(self, recheck_parked: bool = False) -> None:
        """Capacity or membership changed: move parked (unplaceable) PGs
        back into the placer's queue and wake it."""
        with self._pg_cv:
            if recheck_parked and self._pg_parked:
                self._pg_pending.extend(self._pg_parked)
                self._pg_parked.clear()
                self._pg_last_fp = None  # explicit event: force a real pass
            self._pg_cv.notify()

    def _capacity_fingerprint(self):
        """Cheap O(nodes) digest of per-node available resources — the
        placer's 500 ms tick skips re-placing parked PGs when nothing has
        changed since their last failed pass (permanently-unplaceable
        groups must not churn pick_bundle_nodes forever)."""
        with self._lock:
            return tuple(sorted(
                (n.node_id, tuple(sorted(n.available.items())))
                for n in self.nodes.values() if n.alive))

    def _pg_placer_loop(self) -> None:
        """Single placer thread. Placement decisions are serialized, so
        two groups can never race prepare_bundle into mutual abort, and a
        burst of N creations costs N placement passes — not N^2 pool
        submissions. Parked groups (no capacity) retry on cluster events
        and on a 500 ms tick (lease releases free capacity without an
        event)."""
        # graftcheck: disable=GC050 — placer-thread-private fingerprint
        self._pg_last_fp = None
        while True:
            with self._pg_cv:
                while not self._pg_pending and not self._shutdown:
                    if self._pg_parked:
                        tick = float(self.config.pg_placer_tick_s)
                        if not self._pg_cv.wait(tick) \
                                and not self._pg_pending:
                            fp = self._capacity_fingerprint()
                            if fp != self._pg_last_fp:
                                self._pg_pending.extend(self._pg_parked)
                                self._pg_parked.clear()
                                self._pg_last_fp = fp
                    else:
                        self._pg_cv.wait()
                if self._shutdown:
                    return
                pg_id = self._pg_pending.popleft()
            try:
                placed = self._place_pg_once(pg_id)
            except Exception:
                import traceback

                traceback.print_exc()
                placed = False  # park, never drop: a transient error (node
                # channel death mid-prepare) must not strand the PG forever
            if not placed:
                with self._pg_cv:
                    self._pg_parked.add(pg_id)

    def _place_pg_once(self, pg_id: PlacementGroupId) -> bool:
        """One 2PC placement pass. True = done (created, removed, or
        gone); False = no capacity, park for retry."""
        info = self.gcs.get_pg(pg_id)
        if info is None or info.state in ("REMOVED", "CREATED"):
            return True
        placement = self.scheduler.pick_bundle_nodes(
            self._views(), info.bundles, info.strategy)
        if placement is None:
            return self._mark_pg_pending(info)
        # phase 1: prepare all bundles
        prepared = []
        ok = True
        try:
            for idx, nid in enumerate(placement):
                node = self.nodes.get(nid)
                if node is None or not node.prepare_bundle(
                        pg_id, idx, info.bundles[idx]):
                    ok = False
                    break
                prepared.append((node, idx))
        except Exception:
            ok = False
        if not ok:
            for node, idx in prepared:
                node.return_bundle(pg_id, idx)
            return self._mark_pg_pending(info)
        # phase 2: commit. The CREATED transition is serialized with
        # remove_placement_group's REMOVED transition under _pg_cv — an
        # unsynchronized write here could overwrite REMOVED and resurrect
        # a removed group with its bundles reserved forever.
        for node, idx in prepared:
            node.commit_bundle(pg_id, idx)
        info.bundle_nodes = list(placement)
        with self._pg_cv:
            if info.state == "REMOVED":
                removed = True
            else:
                removed = False
                info.state = "CREATED"
        if removed:
            # the remover may have run mid-prepare and seen no
            # bundle_nodes to return — return them here (return_bundle
            # pops its entry, so a double return no-ops)
            for node, idx in prepared:
                node.return_bundle(pg_id, idx)
            return True
        self.gcs.pubsub.publish("pg", (pg_id, "CREATED"))
        try:
            self._reschedule_parked_tasks()
        except Exception:
            pass  # placement bookkeeping is done; scheduling errors
            # surface on the affected tasks, not the placer
        return True

    def _mark_pg_pending(self, info) -> bool:
        """Transition to PENDING unless a concurrent remove won. Returns
        True when the group was removed (caller must NOT park it)."""
        with self._pg_cv:
            if info.state == "REMOVED":
                return True
            info.state = "PENDING"
            return False

    def pg_ready(self, pg_id: PlacementGroupId, timeout: float = 30.0) -> bool:
        """Event-driven: parks on the GCS 'pg' pubsub channel rather than
        polling get_pg (1k concurrent PGs × 100 polls/s was the first
        casualty of SURVEY §6's envelope)."""
        ev = threading.Event()

        def _on_pg(msg) -> None:
            pid, state = msg
            if pid == pg_id and state == "CREATED":
                ev.set()

        unsub = self.gcs.pubsub.subscribe("pg", _on_pg)
        try:
            # check AFTER subscribing: a publish between check and
            # subscribe would otherwise be missed forever
            info = self.gcs.get_pg(pg_id)
            if info is not None and info.state == "CREATED":
                return True
            return ev.wait(timeout)
        finally:
            unsub()

    def remove_placement_group(self, pg_id: PlacementGroupId) -> None:
        info = self.gcs.get_pg(pg_id)
        if info is None:
            return
        with self._pg_cv:
            info.state = "REMOVED"
            try:
                self._pg_pending.remove(pg_id)
            except ValueError:
                pass
            self._pg_parked.discard(pg_id)
        for idx, nid in enumerate(info.bundle_nodes):
            node = self.nodes.get(nid)
            if node is not None:
                node.return_bundle(pg_id, idx)
        # returned bundles free capacity parked PGs may be waiting on
        self._wake_pg_placer(recheck_parked=True)
        # tasks parked against this group must fail (via _schedule's
        # REMOVED check) rather than stay parked forever
        self._reschedule_parked_tasks()

    # ---- worker RPC dispatch (the node-side core-worker service) -------------

    def _handle_client_call(self, client: "_ClientShell", method: str,
                            payload):
        """Remote-driver calls: object payloads travel as bytes (the
        client cannot mmap the head's segments); everything else reuses
        the worker-call surface with the client as the holder identity."""
        head = self.nodes.get(self.head_node_id)
        if method == "client_get_objects":
            out = []
            for oid in payload["ids"]:
                res = self.fetch_one(oid, payload.get("timeout"))
                if res[0] == "inline":
                    out.append(("inline", res[1]))
                else:
                    _, name, size = res
                    mv = self._reader.read(name, size)
                    try:
                        out.append(("inline", bytes(mv[:size])))
                    finally:
                        del mv
                        self._reader.release(name)
            return out
        if method == "client_put":
            oid = payload["object_id"]
            data = payload["data"]
            # the HEAD's config (system_config overrides included), not
            # the module default — DEFAULT doesn't see init() overrides
            if len(data) <= self.config.max_direct_call_object_size:
                self.store_inline_bytes(oid, data)
            else:
                head.store.put_bytes(oid, data, pin=True)
                sh = self._oshard(oid)
                with sh.lock:
                    sh.dir.setdefault(oid, set()).add(head.node_id)
                self._notify_object(oid)
            self.refcount.add_owned(oid)
            self.refcount.add_holder_ref(oid, client.worker_id)
            return True
        return self.handle_worker_call(head, client, method, payload)

    def _block_guard(self, node: Node, worker: Optional[WorkerHandle]):
        """Blocked-worker accounting for worker-originated blocking calls:
        `on_block` (invoked lazily, only if the call actually waits) returns
        the worker's lease resources to its node's pool; `unblock` re-takes
        them on the way out (ref: local_task_manager.cc:57)."""
        state = {"blocked": False}

        def on_block():
            if worker is not None and not state["blocked"]:
                state["blocked"] = True
                node.notify_worker_blocked(worker)

        def unblock():
            if state["blocked"]:
                node.notify_worker_unblocked(worker)

        return on_block, unblock

    def query_logs(self, **kw) -> dict:
        """Attributed log query against the GCS LogStore —
        {"records": [...], "cursor": n}; kwargs are LogStore.query's
        (job/task/actor/worker/node id prefixes, stream, errors_only,
        since, limit, follow_timeout)."""
        return self.gcs.logs.query(**kw)

    def recent_logs(self, worker_id: Optional[str] = None,
                    node_id: Optional[str] = None,
                    pid: Optional[int] = None,
                    limit: int = 500) -> list:
        """Legacy tail view over the attributed store (dashboard log
        view / `util.state.recent_logs`); rows keep the pre-LogStore
        `t` field alongside `ts`."""
        res = self.gcs.logs.query(worker_id=worker_id or None,
                                  node_id=node_id or None,
                                  limit=max(limit, 1)
                                  if not pid else 100000)
        rows = [{**r, "t": r.get("ts")} for r in res["records"]]
        if pid:
            rows = [r for r in rows if r.get("pid") == pid]
        return rows[-limit:]

    def stack_report(self, timeout_s: float = 5.0) -> dict:
        """Merged thread stacks from the driver and EVERY live worker
        (local and remote), fanned out in parallel — the `ray stack`
        analog. Workers that fail to answer in time appear with an
        `error` entry instead of blocking the merge."""
        from ..util.introspect import dump_stacks

        report = {"driver": dump_stacks(), "workers": []}
        targets = []
        for node in list(self.nodes.values()):
            if not node.alive:
                continue
            for w in node.list_workers():
                targets.append((node, w))

        def one(node, w):
            base = {"node_id": node.node_id.hex(),
                    "worker_id": w.worker_id.hex(),
                    "pid": w.pid, "state": w.state,
                    "actor_id": w.actor_id.hex() if w.actor_id else ""}
            try:
                base.update(node.worker_stack(w, timeout=timeout_s))
            except Exception as e:
                base["error"] = repr(e)
            return base

        if targets:
            pool = ThreadPoolExecutor(
                max_workers=min(16, len(targets)),
                thread_name_prefix="stack-fanout")
            try:
                futs = [pool.submit(one, n, w) for n, w in targets]
                for f in futs:
                    try:
                        report["workers"].append(
                            f.result(timeout=timeout_s + 15.0))
                    except Exception as e:  # noqa: BLE001 — merge goes on
                        report["workers"].append({"error": repr(e)})
            finally:
                pool.shutdown(wait=False)
        return report

    def profile_worker(self, worker_id_prefix: str,
                       duration_s: float = 5.0,
                       interval_s: float = 0.01) -> dict:
        """On-demand sampling profile of one live worker, addressed by
        worker-id prefix; returns the collapsed-stack + function table
        result (ray_tpu profile CLI / state API)."""
        for node in list(self.nodes.values()):
            if not node.alive:
                continue
            for w in node.list_workers():
                if w.worker_id.hex().startswith(worker_id_prefix):
                    res = node.worker_profile(w, duration_s=duration_s,
                                              interval_s=interval_s)
                    res["worker_id"] = w.worker_id.hex()
                    res["node_id"] = node.node_id.hex()
                    return res
        raise ValueError(
            f"no live worker with id prefix {worker_id_prefix!r}")

    def _ingest_worker_logs(self, node: Node,
                            worker: Optional[WorkerHandle],
                            payload: dict) -> None:
        """A worker_log batch arrived: stamp node/worker provenance,
        index into the GCS LogStore, and mirror remote stdout/stderr to
        the driver console."""
        from ..util import logs as logs_mod

        recs = payload.get("recs") or ()
        pid = payload.get("pid")
        nhex = node.node_id.hex()
        whex = worker.worker_id.hex() if worker is not None else ""
        out = []
        mirror: Dict[str, list] = {}
        counts: Dict[str, int] = {}
        for rec in recs:
            try:
                stream, seq, ts, job, task, actor, level, line = rec
            except Exception:
                continue  # one malformed record must not drop the batch
            out.append({"ts": ts, "node_id": nhex, "worker_id": whex,
                        "pid": pid, "job_id": job, "task_id": task,
                        "actor_id": actor, "stream": stream,
                        "level": level, "seq": seq, "line": line})
            counts[stream] = counts.get(stream, 0) + 1
            if stream in ("stdout", "stderr"):
                mirror.setdefault(stream, []).append(line)
            elif stream == "log":
                # structured lines (incl. the rpdb connect banner) must
                # reach the driver console too — the remote machine's
                # stderr is invisible to the operator
                mirror.setdefault("log", []).append(
                    f"{level} {line}" if level else line)
        dropped = int(payload.get("dropped") or 0)
        if dropped:
            # surface the gap IN the stream, where a reader will see it
            out.append({"ts": time.time(), "node_id": nhex,
                        "worker_id": whex, "pid": pid, "job_id": "",
                        "task_id": "", "actor_id": "", "stream": "log",
                        "level": "WARNING", "seq": -1,
                        "line": f"[ray_tpu] {dropped} log line(s) dropped "
                                f"by the per-worker rate limit"})
        if not out:
            return
        self.gcs.logs.append(out)
        for stream, n in counts.items():
            logs_mod.LINES_TOTAL.inc(n, tags={"stream": stream})
        if getattr(node, "is_remote", False):
            for stream, lines in mirror.items():
                self._log_mirror.emit(nhex, pid, stream, lines)

    def handle_worker_call(self, node: Node, worker: Optional[WorkerHandle],
                           method: str, payload):
        if method == "get_objects":
            ids = payload["ids"]
            timeout = payload.get("timeout")
            on_block, unblock = self._block_guard(node, worker)
            try:
                return [self.fetch_one(oid, timeout, on_block=on_block)
                        for oid in ids]
            finally:
                unblock()
        if method == "put_inline":
            oid = payload["object_id"]
            self.store_inline_bytes(oid, payload["data"])
            self.refcount.add_owned(oid)
            if worker is not None:
                # the putting worker holds the ref; without this the object
                # has zero counted references and a later unpin frees it
                # out from under the worker (round-1 weak #4)
                self.refcount.add_holder_ref(oid, worker.worker_id)
            return True
        if method == "export_function":
            self.gcs.kv_put("fn:" + payload["func_id"], payload["blob"],
                            namespace="fn", overwrite=False)
            return True
        if method == "get_function":
            return self.get_function_blob(payload)
        if method == "submit_task":
            # the submitting process already counted this task in its own
            # direct/routed split
            refs = self.submit_spec(payload, _count=False)
            if worker is not None:
                # count the submitting worker as holder of the return refs;
                # the transient driver-side refs created by submit_spec are
                # balanced (add_local now, remove_local at GC) and must not
                # be the only thing keeping the results alive
                for r in refs:
                    self.refcount.add_holder_ref(r.id, worker.worker_id)
            return True
        if method == "create_actor":
            self.create_actor(payload["spec"], name=payload.get("name", ""),
                              detached=payload.get("detached", False),
                              meta=payload.get("meta"))
            return True
        if method == "wait":
            refs = [ObjectRef(o) for o in payload["ids"]]
            on_block, unblock = self._block_guard(node, worker)
            try:
                ready, pending = self.wait(refs, payload["num_returns"],
                                           payload.get("timeout"),
                                           on_block=on_block)
            finally:
                unblock()
            return ([r.id for r in ready], [r.id for r in pending])
        if method == "kill_actor":
            self.kill_actor(payload["actor_id"], payload.get("no_restart", True))
            return True
        if method == "cancel_task":
            self.cancel(payload["task_id"], payload.get("force", False))
            return True
        if method == "actor_state":
            return self.actor_state(payload)
        if method == "wait_for_actor":
            on_block, unblock = self._block_guard(node, worker)
            on_block()  # not a hot path: treat the whole call as blocked
            try:
                self.wait_for_actor(payload["actor_id"],
                                    payload.get("timeout", 60.0))
            finally:
                unblock()
            return True
        if method == "get_named_actor":
            info = self.gcs.get_named_actor(payload["name"], payload["namespace"])
            if info is None or info.state == ActorState.DEAD:
                return None
            meta = self.gcs.kv_get("actor_meta:" + info.actor_id.hex(),
                                   namespace="actor")
            return {"actor_id": info.actor_id, "meta": meta}
        if method == "kv_put":
            return self.gcs.kv_put(payload["key"], payload["value"],
                                   namespace=payload.get("namespace", "user"),
                                   overwrite=payload.get("overwrite", True))
        if method == "kv_get":
            return self.gcs.kv_get(payload["key"],
                                   namespace=payload.get("namespace", "user"))
        if method == "kv_del":
            return self.gcs.kv_del(payload["key"],
                                   namespace=payload.get("namespace", "user"))
        if method == "kv_keys":
            return self.gcs.kv_keys(payload.get("prefix", ""),
                                    namespace=payload.get("namespace", "user"))
        if method == "create_pg":
            return self.create_placement_group(payload["bundles"],
                                               payload["strategy"],
                                               payload.get("name", ""))
        if method == "pg_ready":
            on_block, unblock = self._block_guard(node, worker)
            on_block()  # not a hot path: treat the whole call as blocked
            try:
                return self.pg_ready(payload["pg_id"],
                                     payload.get("timeout", 30.0))
            finally:
                unblock()
        if method == "remove_pg":
            self.remove_placement_group(payload["pg_id"])
            return True
        if method == "generator_item":
            # The boolean is the cancellation half of the protocol: False
            # tells the producing worker the consumer dropped the generator.
            return self.on_generator_item(payload["task_id"], payload["index"],
                                          payload["object_id"],
                                          payload.get("data"))
        if method == "generator_next":
            on_block, unblock = self._block_guard(node, worker)
            try:
                ref = self.next_generator_item(payload["task_id"],
                                               payload["index"],
                                               payload.get("timeout"),
                                               on_block=on_block)
            except exc.GetTimeoutError:
                raise
            except BaseException as e:  # generator failed: typed error back
                return ("error", serialization.dumps(e))
            finally:
                unblock()
            if ref is None:
                return ("done", None)
            if worker is not None:
                self.refcount.add_holder_ref(ref.id, worker.worker_id)
            return ("ref", ref.id)
        if method == "release_generator":
            self.release_generator(payload)
            return None
        if method == "add_ref":
            if worker is not None:
                self.refcount.add_holder_ref(payload, worker.worker_id)
            else:
                self.refcount.add_local(payload)
            return None
        if method == "remove_ref":
            if worker is not None:
                self.refcount.remove_holder_ref(payload, worker.worker_id)
            else:
                self.refcount.remove_local(payload)
            return None
        if method == "node_info":
            return {"node_id": node.node_id, "job_id": self.job_id,
                    "namespace": self.namespace}
        if method == "log_event":
            self.gcs.add_task_event(payload)
            return None
        if method == "metrics_push":
            # worker-process metric deltas -> the head's single /metrics
            # exposition, tagged with their origin (the metrics-agent
            # aggregation path; ref: python/ray/_private/metrics_agent.py)
            metrics_mod.merge_remote(
                payload.get("deltas") or [],
                node=node.node_id.hex()[:12],
                worker=(worker.worker_id.hex()[:12]
                        if worker is not None else ""))
            return None
        if method == "task_events":
            return list(self.gcs.task_events())
        if method == "worker_log":
            # attributed log batches: LogStore index + driver mirroring
            self._ingest_worker_logs(node, worker, payload or {})
            return None
        if method == "logs_query":
            return self.query_logs(**(payload or {}))
        if method == "traces_query":
            return self.gcs.traces.query(**(payload or {}))
        if method == "trace_get":
            return self.gcs.traces.get(payload)
        if method == "trace_chrome":
            from ..util.state import _span_trace_events

            tr = self.gcs.traces.get(payload)
            return (_span_trace_events(list(tr.get("spans_detail", ())))
                    if tr else None)
        if method == "cgraph_send":
            # compiled-graph cross-node edge: producer -> head -> consumer
            return self._cgraph_route(payload)
        if method == "resolve_actor":
            # direct dispatch: a worker asks where an actor lives (once
            # per caller x actor x epoch — NOT per call)
            return self.resolve_actor(payload)
        if method == "direct_result_stored":
            # a direct result whose value contains ObjectRefs (or is
            # large): it must live in the head's store so the borrower
            # pins (_nested_refs) protect the nested objects exactly as
            # the routed path does
            oid = payload["object_id"]
            nested = payload.get("borrowed") or []
            if nested:
                sh = self._oshard(oid)
                with sh.lock:
                    sh.nested.setdefault(oid, []).extend(nested)
                for n in nested:
                    self.refcount.add_local(n)
            self.store_inline_bytes(oid, payload["data"])
            self.refcount.add_owned(oid)
            return True
        if method == "task_events_batch":
            # batched lifecycle events for direct-path tasks: the head
            # learns of completions in one message per interval instead
            # of per-call GCS traffic
            self.gcs.add_task_events(payload or [])
            return None
        raise ValueError(f"unknown worker call: {method}")

    # ---- compiled graphs (ray_tpu/cgraph) ------------------------------------

    def _cgraph_register(self, dag) -> None:
        with self._lock:
            self._cgraphs[dag.graph_id] = dag
            for akey in dag._actor_plans:
                self._cgraph_actors[akey] = dag.graph_id

    def _cgraph_unregister(self, dag) -> None:
        with self._lock:
            self._cgraphs.pop(dag.graph_id, None)
            for akey in [k for k, g in self._cgraph_actors.items()
                         if g == dag.graph_id]:
                self._cgraph_actors.pop(akey, None)
            for cid in [c for c, r in self._cgraph_routes.items()
                        if r[3] == dag.graph_id]:
                self._cgraph_routes.pop(cid, None)

    def _cgraph_actor_in_use(self, actor_id: ActorId) -> bool:
        with self._lock:
            return actor_id.binary() in self._cgraph_actors

    def _cgraph_route(self, payload: dict) -> bool:
        """Route one cross-node compiled-graph envelope: a producer
        worker shipped it up its node channel; deliver it to the
        consumer process (driver queue, or a worker's cgraph_push)."""
        with self._lock:
            route = self._cgraph_routes.get(payload["cid"])
        if route is None:
            return False  # late send after teardown: drop
        kind, target, worker, gid = route
        msg = {"graph_id": gid, "cid": payload["cid"],
               "seq": payload["seq"], "data": payload["data"]}
        if kind == "driver":
            target._deliver(payload["cid"], payload["seq"],
                            payload["data"])
        else:
            target.worker_notify(worker, "cgraph_push", msg)
        return True

    # ---- cancellation --------------------------------------------------------

    def cancel(self, task_id_or_ref, force: bool = False) -> None:
        if isinstance(task_id_or_ref, ObjectRef):
            spec = self.task_manager.lineage_for_object(task_id_or_ref.id)
        else:
            pt = self.task_manager.get(task_id_or_ref)
            spec = pt.spec if pt else None
        if spec is None:
            return
        pt = self.task_manager.get(spec.task_id)
        if pt is None:
            return
        pt.retries_left = 0
        found_running = False
        for node in list(self.nodes.values()):
            for w in list(node._workers.values()):
                if spec.task_id in w.in_flight:
                    found_running = True
                    if force:
                        node.kill_worker(w, force=True)
                    elif w.channel is not None:
                        w.channel.notify("cancel_task", spec.task_id)
        if not found_running:
            self._fail_task(spec, exc.TaskCancelledError(
                f"Task {spec.description} cancelled before execution"))

    # ---- context & lifecycle -------------------------------------------------

    def runtime_context(self) -> RuntimeContext:
        return RuntimeContext(job_id=self.job_id, node_id=self.head_node_id,
                              worker_id=self.worker_id, namespace=self.namespace)

    def cluster_resources(self) -> ResourceSet:
        total: ResourceSet = {}
        for n in self.nodes.values():
            if n.alive:
                for k, v in n.total_resources.items():
                    total[k] = total.get(k, 0) + v
        return total

    def available_resources(self) -> ResourceSet:
        total: ResourceSet = {}
        for n in self.nodes.values():
            if n.alive:
                for k, v in n.available.items():
                    total[k] = total.get(k, 0) + v
        return total

    def shutdown(self) -> None:
        """Idempotent and race-safe: concurrent callers (atexit hook vs
        signal handler vs explicit call) serialize on the shutdown lock —
        the loser blocks until teardown actually finished instead of
        returning while nodes/channels are still being released. A
        REENTRANT call from the same thread (a signal delivered inside
        shutdown, or an on_close callback calling back in) returns
        immediately: blocking would self-deadlock."""
        # no unlocked fast path on the _shutdown flag: the flag is set
        # BEFORE the body runs, so a concurrent caller reading it early
        # would return while teardown is still in progress — it must
        # block on the lock below instead
        if not self._shutdown_lock.acquire(blocking=False):
            # a true compare only ever observes the reading thread's own
            # earlier write, so reading the owner field unlocked is safe
            # graftcheck: disable=GC050 — reentrancy probe
            if self._shutdown_owner == threading.get_ident():
                return  # reentrant (signal handler / close callback)
            with self._shutdown_lock:  # concurrent: wait for completion
                return
        try:
            if self._shutdown:
                return
            self._shutdown_owner = threading.get_ident()
            self._shutdown = True
            self._shutdown_body()
        finally:
            self._shutdown_owner = None
            self._shutdown_lock.release()

    def _shutdown_body(self) -> None:
        for dag in list(self._cgraphs.values()):
            try:
                dag.teardown()  # release channel segments + stop loops
            except Exception:
                pass
        for rec in list(self._actors.values()):
            chan = rec.direct_chan
            if chan is not None:
                try:
                    chan.close()
                except Exception:
                    pass
        with self._pg_cv:
            self._pg_cv.notify()
        for node in list(self.nodes.values()):
            try:
                node.shutdown(kill=False)
            except Exception:
                pass
        if getattr(self, "_remote_server", None) is not None:
            try:
                self._remote_server.close()
            except Exception:
                pass
        self.gcs.finish_job(self.job_id)
        self.gcs.stop()
        self._reader.close()
        self._pool.shutdown(wait=False)


class _TaskCtx:
    __slots__ = ("spec", "put_index")

    def __init__(self, spec: TaskSpec):
        self.spec = spec
        self.put_index = 0


class _ClientShell:
    """Holder identity + no-op lease surface for a remote-driver client
    (quacks enough like a WorkerHandle for handle_worker_call and
    _block_guard; clients hold no lease, so blocking accounting no-ops)."""

    __slots__ = ("worker_id", "lease_resources", "state", "blocked_depth")

    def __init__(self, worker_id: WorkerId):
        self.worker_id = worker_id
        self.lease_resources: dict = {}
        self.state = "client"
        self.blocked_depth = 0


class _WorkerDirectState:
    """Worker-side half of decentralized dispatch (docs/DISPATCH.md).

    A worker calling ``handle.method.remote()`` resolves the actor's
    placement ONCE through the head, then submits every subsequent call
    straight to the owning worker over a cached peer connection — zero
    head RPCs in steady state. Results come back inline on the peer
    channel and are resolved from a local table; refs that ESCAPE this
    process (task args, values put/returned containing them) are first
    published to the head so the rest of the cluster can see them. Any
    peer failure falls back to the routed path."""

    def __init__(self, wr: "WorkerRuntime"):
        self.wr = wr
        self._lock = instrumented_lock("worker.direct")
        self._actors: Dict[ActorId, dict] = {}   # actor -> cache entry
        self._peers: Dict[str, Any] = {}         # addr -> RpcChannel
        self._rows: Dict[ObjectId, dict] = {}    # return oid -> row
        self._tasks: Dict[TaskId, dict] = {}     # task_id -> task row

    # -- submission -----------------------------------------------------------

    def try_submit(self, spec: TaskSpec) -> Optional[List[ObjectRef]]:
        if not DriverRuntime._direct_eligible(spec):
            return None
        entry = self._entry_for(spec.actor_id)
        if entry is None:
            return None
        chan = entry["chan"]
        ev = threading.Event()
        trow = {"spec": spec, "event": ev, "done": False, "chan": chan,
                "actor_id": spec.actor_id}
        with self._lock:
            if not entry.get("ok"):
                return None
            spec.owner_id = self.wr.worker_id
            spec.seq_no = entry["seq"]
            entry["seq"] += 1
            gate, era = entry["gate"], entry["lane"]
            self._tasks[spec.task_id] = trow
            for oid in spec.return_ids():
                self._rows[oid] = {"state": "pending", "data": None,
                                   "trow": trow, "head_ref": False}
        chan.notify("direct_submit", {"spec": spec, "gate": gate,
                                      "lane": era})
        _C_DIRECT.inc()
        _rec_dispatch("direct", spec)
        if chan.closed:
            # raced the peer's death: on_close may have swept before our
            # rows registered — run the fallback for this task explicitly
            self._fallback_task(trow)
        refs = []
        for oid in spec.return_ids():
            ref = ObjectRef(oid)
            weakref.finalize(ref, self._drop, oid)
            refs.append(ref)
        return refs

    def _entry_for(self, actor_id: ActorId) -> Optional[dict]:
        with self._lock:
            entry = self._actors.get(actor_id)
            if entry is not None and entry.get("ok") \
                    and not entry["chan"].closed \
                    and not entry.get("stale_gate"):
                return entry
            if entry is not None and entry.get("bad_until", 0) \
                    > time.monotonic():
                return None  # negative cache: don't pay a resolve RPC
                # per call while the actor stays routed-only
        try:
            res = self.wr.channel.call("resolve_actor", actor_id, timeout=30)
        except Exception:
            res = None
        with self._lock:
            old = self._actors.get(actor_id)
            if res is None or not res.get("addr"):
                # mutate the EXISTING entry in place (never replace it:
                # an in-flight try_submit may hold the dict — a fresh
                # copy forks the seq counter, and in-place mutation is
                # also what makes its ok-recheck see this failure)
                if old is None:
                    old = {"seq": 0, "lane": 0, "chan": None,
                           "epoch": -1}
                    self._actors[actor_id] = old
                old["ok"] = False
                old["bad_until"] = time.monotonic() + 0.5
                return None
        chan = self._peer(res["addr"])
        if chan is None:
            with self._lock:
                old = self._actors.get(actor_id)
                if old is None:
                    old = {"seq": 0, "lane": 0}
                    self._actors[actor_id] = old
                fails = old.get("fails", 0)
                old["ok"] = False
                old["bad_until"] = time.monotonic() \
                    + _DIRECT_RECONNECT.backoff(fails)
                old["fails"] = fails + 1
                old["epoch"] = res["epoch"]
                # the old socket is gone: dropping the chan forces the
                # recovery path into a new lane era (seq restarts there)
                old.pop("chan", None)
            return None
        with self._lock:
            old = self._actors.get(actor_id) or {}
            # same epoch over the SAME live connection: the worker's lane
            # for this caller survives — seq continues (a restart would
            # collide with frames already buffered there), so the entry
            # is refreshed IN PLACE. Replacing the dict forked the seq
            # counter: a racing try_submit (first-call burst, or a
            # stale_gate refresh racing an in-flight call) still held
            # the old dict, two frames went out with the same lane+seq,
            # the receiver dropped one as a duplicate and that caller
            # hung to its get() timeout (found by scripts/locks_gate.py:
            # instrumented-lock overhead widens the window to every run).
            if old.get("epoch") == res["epoch"] and old.get("chan") is chan:
                old.update({"ok": True, "addr": res["addr"],
                            "gate": res["gate"], "actor_id": actor_id,
                            "chan": chan, "epoch": res["epoch"]})
                old.pop("stale_gate", None)
                old.setdefault("lane", 0)
                old.setdefault("seq", 0)
                self._actors[actor_id] = old
                return old
            # a new channel is a new era: frames lost in the old socket
            # would strand the receiver's expected counter, so bump the
            # lane and restart seq (the receiver resets on a higher era)
            entry = {"ok": True, "addr": res["addr"], "chan": chan,
                     "epoch": res["epoch"], "gate": res["gate"],
                     "actor_id": actor_id,
                     "lane": old.get("lane", 0) + 1, "seq": 0}
            self._actors[actor_id] = entry
            return entry

    def note_routed(self, actor_id: Optional[ActorId]) -> None:
        """A routed actor submission happened (streaming / ref args): the
        cached gate no longer covers it — force a re-resolve (fresh gate,
        same lane) before the next direct call so per-caller FIFO holds."""
        if actor_id is None:
            return
        with self._lock:
            entry = self._actors.get(actor_id)
            if entry is not None and entry.get("ok"):
                entry["stale_gate"] = True

    def _peer(self, addr: str):
        with self._lock:
            ch = self._peers.get(addr)
            if ch is not None and not ch.closed:
                return ch
        from .rpc import connect as _rpc_connect

        try:
            ch = _rpc_connect(addr, handler=self._peer_handler, name="dpeer")
        except Exception:
            return None
        ch.on_close(lambda a=addr, c=ch: self._on_peer_close(a, c))
        dup = None
        with self._lock:
            old = self._peers.get(addr)
            if old is not None and not old.closed:
                dup = ch
                ch = old
            else:
                self._peers[addr] = ch
        if dup is not None:
            # lost the connect race: close the duplicate OUTSIDE the
            # lock — close() runs on_close callbacks synchronously, and
            # _on_peer_close takes the same (non-reentrant) lock. Closing
            # under the lock self-deadlocked every router thread in the
            # process (100-in-flight serve load on multi-core boxes).
            dup.close()
        return ch

    def _peer_handler(self, method: str, payload):
        if method == "direct_result":
            self.on_direct_result(payload)
            return None
        raise ValueError(f"unknown direct peer message {method}")

    # -- results --------------------------------------------------------------

    def on_direct_result(self, payload: dict) -> None:
        with self._lock:
            trow = self._tasks.pop(payload["task_id"], None)
            if trow is None or trow["done"]:
                return
            trow["done"] = True
            spec = trow["spec"]
            if payload.get("stale"):
                entry = self._actors.get(spec.actor_id)
                if entry is not None:
                    entry["ok"] = False
                stale = True
            else:
                stale = False
                error = payload.get("error")
                rids = spec.return_ids()
                results = payload.get("results") or []
                add_refs = []
                for i, oid in enumerate(rids):
                    row = self._rows.get(oid)
                    if row is None:
                        continue
                    if error is not None:
                        row["state"] = "error"
                        row["data"] = error
                    elif i < len(results) and results[i][0] == "inline":
                        row["state"] = "done"
                        row["data"] = results[i][1]
                    else:
                        # ("stored"): the head's store owns it — count
                        # this process as holder for the ref's lifetime
                        row["state"] = "stored"
                        row["head_ref"] = True
                        add_refs.append(oid)
        if stale:
            self._fallback_task(trow)
            return
        for oid in add_refs:
            try:
                self.wr.channel.notify("add_ref", oid)
            except Exception:
                pass
        trow["event"].set()

    def _on_peer_close(self, addr: str, ch=None) -> None:
        with self._lock:
            # identity check: a duplicate connection losing the connect
            # race must not evict the winner from the cache (mirrors the
            # driver-side _on_direct_peer_close hardening)
            if ch is None or self._peers.get(addr) is ch:
                self._peers.pop(addr, None)
            victims = [t for t in self._tasks.values()
                       if not t["done"] and t["chan"].closed]
            for e in self._actors.values():
                if e.get("ok") and e.get("addr") == addr \
                        and (ch is None or e.get("chan") is ch):
                    e["ok"] = False
        for trow in sorted(victims, key=lambda t: t["spec"].seq_no):
            self._fallback_task(trow)

    def _fallback_task(self, trow: dict) -> None:
        """Peer died / stale placement: resubmit through the head, which
        owns restart/death semantics. Idempotent per task. Mirrors the
        driver's retry rule: a task whose worker died re-runs only with a
        retry budget (or when the actor is in fact still ALIVE — lost
        connection, not a death); otherwise it fails typed."""
        with self._lock:
            if trow.get("routed"):
                return
            if not trow["done"]:
                self._tasks.pop(trow["spec"].task_id, None)
                trow["done"] = True
            trow["routed"] = True
            spec = trow["spec"]
            rows = [self._rows.get(oid) for oid in spec.return_ids()]
        if spec.max_retries == 0:
            try:
                alive = self.wr.channel.call(
                    "actor_state", spec.actor_id, timeout=30) == "ALIVE"
            except Exception:
                alive = False
            if not alive:
                blob = serialization.dumps(exc.ActorDiedError(
                    f"Actor {spec.actor_id.hex()[:8]} died while running "
                    f"{spec.description}"))
                with self._lock:
                    for row in rows:
                        if row is not None and row["state"] == "pending":
                            row["state"] = "error"
                            row["data"] = blob
                trow["event"].set()
                return
        import copy

        spec = copy.copy(spec)  # the direct frame may still be queued
        spec.owner_id = None
        spec.seq_no = 0
        _C_ROUTED.inc()
        try:
            self.wr.channel.call("submit_task", spec)
        except Exception:
            # head unreachable too: the worker is dying; leave rows
            # pending — getters time out
            return
        with self._lock:
            for row in rows:
                if row is not None and row["state"] == "pending":
                    # the head now counts this worker as holder of the
                    # return refs (submit_task handler)
                    row["state"] = "routed"
                    row["head_ref"] = True
        trow["event"].set()

    def _drop(self, oid: ObjectId) -> None:
        with self._lock:
            row = self._rows.pop(oid, None)
        if row is not None and row.get("head_ref"):
            try:
                self.wr.channel.notify("remove_ref", oid)
            except Exception:
                pass

    # -- resolution into the get/wait planes ----------------------------------

    def involves(self, oids) -> bool:
        with self._lock:
            return any(o in self._rows for o in oids)

    def ensure_published(self, oid: ObjectId) -> None:
        """This ref is escaping the process (task arg, nested in a put or
        a return): the head must own a copy first, or the consumer's
        fetch would hang on an object only this process knows about.
        Blocks until the direct result arrives if it is still in flight."""
        with self._lock:
            row = self._rows.get(oid)
            if row is None or row.get("published") or row.get("head_ref"):
                return
            trow = row["trow"]
        trow["event"].wait(300)
        with self._lock:
            if row.get("published") or row["state"] not in ("done", "error"):
                return  # stored/routed rows already live head-side;
                # error blobs publish too (the consumer must see the
                # typed failure, not hang)
            data = row["data"]
            row["published"] = True
            row["head_ref"] = True
        try:
            self.wr.channel.call("put_inline", {"object_id": oid,
                                                "data": data})
        except Exception:
            pass

    def get_many(self, oids: List[ObjectId], timeout: Optional[float]):
        """Resolve direct-result oids locally; delegate the rest to the
        head. Returns fetch-result tuples aligned with oids (the caller
        deserializes)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        out: Dict[int, Tuple] = {}
        head_ids: List[Tuple[int, ObjectId]] = []
        for i, oid in enumerate(oids):
            with self._lock:
                row = self._rows.get(oid)
            if row is None:
                head_ids.append((i, oid))
                continue
            remaining = (None if deadline is None
                         else max(0.0, deadline - time.monotonic()))
            if not row["trow"]["event"].wait(remaining):
                raise exc.GetTimeoutError(
                    f"Get timed out waiting for object {oid.hex()[:12]}")
            with self._lock:
                state, data = row["state"], row["data"]
            if state in ("done", "error"):
                out[i] = ("inline", data)
            else:  # stored / routed / pending-after-fallback: head-side
                head_ids.append((i, oid))
        if head_ids:
            remaining = (None if deadline is None
                         else max(0.0, deadline - time.monotonic()))
            fetched = self.wr.channel.call(
                "get_objects", {"ids": [o for _, o in head_ids],
                                "timeout": remaining}, timeout=None)
            for (i, _), res in zip(head_ids, fetched):
                out[i] = res
        return [out[i] for i in range(len(oids))]

    def wait(self, refs, num_returns: int, timeout: Optional[float]):
        """wait() over a mix of local direct results and head-side refs."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            ready, pending = [], []
            head_pending = []
            for r in refs:
                with self._lock:
                    row = self._rows.get(r.id)
                if row is None or row["state"] in ("stored", "routed"):
                    head_pending.append(r)
                    pending.append(r)
                elif row["trow"]["event"].is_set():
                    ready.append(r)
                else:
                    pending.append(r)
            if len(ready) >= num_returns or not pending:
                return ready[:num_returns], \
                    [r for r in refs if r not in ready[:num_returns]]
            remaining = (None if deadline is None
                         else deadline - time.monotonic())
            if remaining is not None and remaining <= 0:
                return ready, pending
            if head_pending:
                if len(head_pending) < len(pending):
                    # mixed wait: short head slices so a local direct
                    # result firing mid-wait can still cut it short
                    slice_t = 0.1 if remaining is None \
                        else max(0.0, min(0.1, remaining))
                else:
                    # every pending ref is head-side: nothing local can
                    # change, so ONE blocking call with the full budget
                    # (the head's wait is event-driven) — not a 100 ms
                    # poll loop multiplying head traffic per waiter
                    slice_t = remaining
                ready_ids, _ = self.wr.channel.call(
                    "wait", {"ids": [r.id for r in head_pending],
                             "num_returns": min(num_returns - len(ready),
                                                len(head_pending)),
                             "timeout": slice_t}, timeout=None)
                ready_set = set(ready_ids)
                newly = [r for r in head_pending if r.id in ready_set]
                if newly:
                    ready.extend(newly)
                    if len(ready) >= num_returns:
                        return ready[:num_returns], \
                            [r for r in refs if r not in ready[:num_returns]]
            else:
                # purely local: park on the first pending event briefly
                first = next((r for r in pending), None)
                with self._lock:
                    row = self._rows.get(first.id) if first else None
                if row is not None:
                    slice_t = 0.1 if remaining is None \
                        else max(0.0, min(0.1, remaining))
                    row["trow"]["event"].wait(slice_t)


class WorkerRuntime:
    """Thin runtime inside worker processes: proxies the core API over the
    node channel (the analog of _raylet.pyx calling into CoreWorker)."""

    def __init__(self, worker_process):
        self.worker = worker_process
        self.channel = worker_process.channel
        # contextvars, not thread-locals: async-actor coroutines interleave
        # on one event-loop thread, but each asyncio.Task carries its own
        # Context, so per-task state stays isolated
        self._current: "contextvars.ContextVar[Optional[_TaskCtx]]" = \
            contextvars.ContextVar("rtpu_current_task", default=None)
        self._fn_cache: Dict[int, tuple] = {}
        self._put_lock = instrumented_lock("worker.put_counter")
        self._put_counter = 0
        self.worker_id = worker_process.worker_id
        self._held_lock = instrumented_lock("worker.held_refs")
        self._held: Dict[ObjectId, int] = {}
        from .config import DEFAULT as _cfg

        self._direct = (_WorkerDirectState(self)
                        if int(_cfg.direct_actor_calls) else None)

    # -- worker-held reference accounting (ref: reference_count.h:61 borrower
    # reports; the head aggregates per-holder counts and frees only when all
    # holders have dropped theirs) ------------------------------------------

    def adopt_owned_ref(self, ref: ObjectRef) -> None:
        """A ref whose holder-count the head already established (task
        submission returns, puts): only attach the decrement finalizer."""
        with self._held_lock:
            self._held[ref.id] = self._held.get(ref.id, 0) + 1
        weakref.finalize(ref, self._deref, ref.id)

    def register_borrowed_ref(self, ref: ObjectRef) -> None:
        """A ref deserialized in this worker (task arg or inside a fetched
        value): report the borrow to the head, then track like any ref."""
        with self._held_lock:
            self._held[ref.id] = self._held.get(ref.id, 0) + 1
        try:
            self.channel.notify("add_ref", ref.id)
        except Exception:
            pass
        weakref.finalize(ref, self._deref, ref.id)

    def _deref(self, oid: ObjectId) -> None:
        with self._held_lock:
            c = self._held.get(oid, 0) - 1
            if c <= 0:
                self._held.pop(oid, None)
            else:
                self._held[oid] = c
        try:
            self.channel.notify("remove_ref", oid)
        except Exception:
            pass

    # task context
    def set_current_task(self, spec: TaskSpec):
        return self._current.set(_TaskCtx(spec))

    def clear_current_task(self, token) -> None:
        self._current.reset(token)

    def current_task(self) -> Optional[TaskSpec]:
        ctx = self._current.get()
        return ctx.spec if ctx is not None else None

    # objects
    def next_put_id(self) -> ObjectId:
        # Per-task deterministic put indices: a re-executed task (lineage
        # reconstruction) recreates byte-identical put ObjectIds, making
        # objects put inside tasks reconstructable — stronger than the
        # reference, where ray.put objects are unrecoverable.
        ctx = self._current.get()
        if ctx is not None:
            ctx.put_index += 1
            return ObjectId.for_put(ctx.spec.task_id, ctx.put_index)
        with self._put_lock:
            self._put_counter += 1
            return ObjectId.for_put(TaskId.from_random(), self._put_counter)

    def put(self, value: Any) -> ObjectRef:
        from .config import DEFAULT as cfg

        oid = self.next_put_id()
        sobj = serialization.serialize(value)
        for r in sobj.contained_refs:
            # direct results nested in a put value escape this process
            self.ensure_published(r.id)
        if sobj.total_bytes <= cfg.max_direct_call_object_size:
            self.channel.call("put_inline", {"object_id": oid,
                                             "data": sobj.to_bytes()})
        else:
            name = self.channel.call("create_object",
                                     {"object_id": oid, "size": sobj.total_bytes})
            mv = self.worker.reader.read(name, sobj.total_bytes)
            sobj.write_into(mv)
            del mv  # drop the exported view before unmapping
            self.worker.reader.release(name)
            # is_put: the worker holds the only reference (balanced by
            # adopt_owned_ref below); task RETURNS also seal but their
            # lifetime is owned by the caller's returned refs instead.
            self.channel.call("seal_object", {"object_id": oid,
                                              "is_put": True})
        ref = ObjectRef(oid)
        self.adopt_owned_ref(ref)
        return ref

    def get_many(self, oids: List[ObjectId], timeout: Optional[float] = None):
        t0 = time.perf_counter()
        try:
            if self._direct is not None and self._direct.involves(oids):
                results = self._direct.get_many(oids, timeout)
            else:
                results = self.channel.call("get_objects",
                                            {"ids": oids, "timeout": timeout},
                                            timeout=None)
        finally:
            # worker-local registry: ships to the head node/worker-tagged
            _H_GET_WAIT.observe(time.perf_counter() - t0)
        out = []
        for res in results:
            out.append(self._deserialize(res))
        return out

    def on_direct_result(self, payload: dict) -> None:
        """direct_result frames arriving on the NODE channel (a peer that
        replied through it) route here from WorkerProcess.handle_direct."""
        if self._direct is not None:
            self._direct.on_direct_result(payload)

    def ensure_published(self, oid: ObjectId) -> None:
        """A ref is escaping this process (task arg / nested in a put or
        return): make sure the head owns the object first. No-op for
        anything that isn't a locally-held direct result."""
        if self._direct is not None:
            self._direct.ensure_published(oid)

    def _deserialize(self, res):
        if res[0] == "inline":
            value = serialization.loads(res[1])
        else:
            _, name, size = res
            value = serialization.loads(self.worker.reader.read(name, size))
        if isinstance(value, exc.TaskError):
            cause = value.cause
            if isinstance(cause, exc.RayTpuError):
                raise cause
            raise value
        if isinstance(value, exc.RayTpuError):
            raise value
        return value

    def get(self, refs, timeout: Optional[float] = None):
        single = isinstance(refs, ObjectRef)
        if single:
            refs = [refs]
        out = self.get_many([r.id for r in refs], timeout)
        return out[0] if single else out

    def get_async(self, ref: ObjectRef):
        import asyncio

        loop = asyncio.get_event_loop()
        return loop.run_in_executor(None, lambda: self.get(ref))

    def wait(self, refs, num_returns=1, timeout=None, fetch_local=True):
        if self._direct is not None \
                and self._direct.involves([r.id for r in refs]):
            return self._direct.wait(refs, num_returns, timeout)
        ready_ids, pending_ids = self.channel.call(
            "wait", {"ids": [r.id for r in refs], "num_returns": num_returns,
                     "timeout": timeout}, timeout=None)
        ready_set = {o for o in ready_ids}
        ready = [r for r in refs if r.id in ready_set]
        pending = [r for r in refs if r.id not in ready_set]
        return ready, pending

    # functions / tasks / actors
    def export_function(self, fn) -> str:
        key = id(fn)
        cached = self._fn_cache.get(key)
        if cached is not None and cached[0] is fn:
            return cached[1]
        blob = cloudpickle.dumps(fn)
        func_id = hashlib.sha1(blob).hexdigest()
        self.channel.call("export_function", {"func_id": func_id, "blob": blob})
        self._fn_cache[key] = (fn, func_id)
        return func_id

    def new_task_id(self) -> TaskId:
        return TaskId.from_random()

    def submit_spec(self, spec: TaskSpec) -> List[ObjectRef]:
        if spec.task_type == TaskType.ACTOR_TASK and self._direct is not None:
            refs = self._direct.try_submit(spec)
            if refs is not None:
                return refs
            # routed actor call (streaming / ref args / not resolvable):
            # the cached direct gate no longer covers it
            self._direct.note_routed(spec.actor_id)
        _C_ROUTED.inc()
        _rec_dispatch("routed", spec)
        refs = [ObjectRef(oid) for oid in spec.return_ids()]
        self.channel.call("submit_task", spec)
        # the head counted this worker as holder of each return ref during
        # the submit call; pair each with a GC-driven decrement
        for r in refs:
            self.adopt_owned_ref(r)
        return refs

    def create_actor(self, spec: TaskSpec, name: str = "", detached: bool = False,
                     meta: Optional[dict] = None) -> None:
        self.channel.call("create_actor", {"spec": spec, "name": name,
                                           "detached": detached, "meta": meta})

    def kill_actor(self, actor_id: ActorId, no_restart: bool = True) -> None:
        self.channel.call("kill_actor", {"actor_id": actor_id,
                                         "no_restart": no_restart})

    def actor_state(self, actor_id: ActorId) -> str:
        return self.channel.call("actor_state", actor_id)

    def wait_for_actor(self, actor_id: ActorId, timeout: float = 60.0) -> None:
        self.channel.call("wait_for_actor", {"actor_id": actor_id,
                                             "timeout": timeout}, timeout=None)

    def get_named_actor_info(self, name: str, namespace: str):
        return self.channel.call("get_named_actor", {"name": name,
                                                     "namespace": namespace})

    def cancel(self, ref, force: bool = False) -> None:
        self.channel.call("cancel_task", {"task_id": ref, "force": force})

    def free(self, refs) -> None:
        pass  # centralized GC; workers do not free directly

    # placement groups
    def create_placement_group(self, bundles, strategy, name=""):
        return self.channel.call("create_pg", {"bundles": bundles,
                                               "strategy": strategy, "name": name})

    def pg_ready(self, pg_id, timeout: float = 30.0) -> bool:
        return self.channel.call("pg_ready", {"pg_id": pg_id, "timeout": timeout},
                                 timeout=None)

    def remove_placement_group(self, pg_id) -> None:
        self.channel.call("remove_pg", {"pg_id": pg_id})

    # kv
    def next_generator_item(self, task_id, index: int,
                            timeout: Optional[float] = None):
        kind, val = self.channel.call(
            "generator_next",
            {"task_id": task_id, "index": index, "timeout": timeout})
        if kind == "done":
            return None
        if kind == "error":
            err = serialization.loads(val)
            raise err if isinstance(err, BaseException) else \
                exc.TaskError(cause=RuntimeError(str(err)))
        ref = ObjectRef(val)
        self.adopt_owned_ref(ref)  # head counted this worker as holder
        return ref

    def release_generator(self, task_id) -> None:
        self.channel.notify("release_generator", task_id)

    def prepare_runtime_env(self, renv: Optional[dict]) -> Optional[dict]:
        """Nested submission: no env specified inherits the parent task's
        (already-packaged) env — the worker IS that environment; an explicit
        env is packaged fresh (reference semantics: a task-level env
        replaces, not composes)."""
        if not renv:
            cur = self.current_task()
            return cur.runtime_env if cur is not None else None
        from . import runtime_env as renv_mod

        validated = renv_mod.validate(renv)
        key = renv_mod.cache_key(validated)
        cache = getattr(self, "_renv_cache", None)
        if cache is None:
            cache = self._renv_cache = {}
        cached = cache.get(key)
        if cached is None:
            cached = cache[key] = renv_mod.package(
                validated,
                lambda k, b: self.kv_put(k, b,
                                         namespace=renv_mod.KV_NAMESPACE,
                                         overwrite=False))
        return cached

    def kv_put(self, key, value, namespace="user", overwrite=True):
        return self.channel.call("kv_put", {"key": key, "value": value,
                                            "namespace": namespace,
                                            "overwrite": overwrite})

    def kv_get(self, key, namespace="user"):
        return self.channel.call("kv_get", {"key": key, "namespace": namespace})

    def kv_del(self, key, namespace="user"):
        return self.channel.call("kv_del", {"key": key, "namespace": namespace})

    def kv_keys(self, prefix="", namespace="user"):
        return self.channel.call("kv_keys", {"prefix": prefix,
                                             "namespace": namespace})

    def runtime_context(self) -> RuntimeContext:
        spec = self.current_task()
        info = self.channel.call("node_info", {})
        return RuntimeContext(
            job_id=info["job_id"], node_id=info["node_id"],
            worker_id=self.worker_id,
            task_id=spec.task_id if spec else None,
            actor_id=spec.actor_id if spec else None,
            namespace=info["namespace"])

    def shutdown(self) -> None:
        pass
