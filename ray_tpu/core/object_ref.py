"""ObjectRef: a future-like handle to an object in the cluster.

Equivalent of the reference's ObjectRef (ref: python/ray/_raylet.pyx ObjectRef
cdef class; ownership semantics per src/ray/core_worker/reference_count.h:61 —
every ref carries its owner's identity so borrowers can locate the value and
report their references)."""
from __future__ import annotations

from typing import Optional

from .ids import ObjectId, TaskId, WorkerId


# Per-process hook invoked for every ObjectRef materialized by
# DESERIALIZATION (not plain construction). Workers install it to report
# borrowed references to the head; the driver installs it to count refs it
# receives inside fetched values (ref: _private/serialization.py in-band
# ObjectRef tracking for the borrowing protocol).
_borrow_hook = None


def _set_borrow_hook(hook) -> None:
    global _borrow_hook
    _borrow_hook = hook


def _reconstruct_ref(object_id, owner, call_site):
    ref = ObjectRef(object_id, owner, call_site)
    hook = _borrow_hook
    if hook is not None:
        try:
            hook(ref)
        except Exception:
            pass
    return ref


class ObjectRefGenerator:
    """Iterator over a streaming task's yielded items
    (ref: python/ray/_raylet.pyx ObjectRefGenerator /
    core_worker.proto:436). Each __next__ blocks until the worker reports
    the next item, then returns an ObjectRef to it. Usable in the process
    that submitted the task."""

    def __init__(self, task_id, runtime):
        import weakref

        self._task_id = task_id
        self._rt = runtime
        self._index = 0
        # free never-consumed items when the generator is dropped
        weakref.finalize(self, runtime.release_generator, task_id)

    def __iter__(self):
        return self

    def __next__(self):
        return self.next()

    def next(self, timeout: Optional[float] = None):
        """`__next__` with a deadline: raises GetTimeoutError if the
        producer yields nothing within `timeout` seconds. Lets blocking
        consumers (Serve proxies) bound how long a hung replica can pin
        their thread."""
        ref = self._rt.next_generator_item(self._task_id, self._index,
                                           timeout=timeout)
        if ref is None:
            raise StopIteration
        self._index += 1
        return ref

    def completed(self) -> int:
        """Items consumed so far."""
        return self._index

    def __repr__(self):
        return f"ObjectRefGenerator({self._task_id.hex()[:12]}, i={self._index})"


class ObjectRef:
    __slots__ = ("id", "owner", "_call_site", "__weakref__")

    def __init__(self, object_id: ObjectId, owner: Optional[WorkerId] = None,
                 call_site: str = ""):
        self.id = object_id
        self.owner = owner
        self._call_site = call_site

    def hex(self) -> str:
        return self.id.hex()

    def binary(self) -> bytes:
        return self.id.binary()

    def task_id(self) -> bytes:
        return self.id.task_prefix()

    def __hash__(self):
        return hash(self.id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other.id == self.id

    def __repr__(self):
        return f"ObjectRef({self.id.hex()})"

    def __reduce__(self):
        # Serialization of a ref hands out a *borrowed* reference; the
        # deserializing process's _borrow_hook reports the borrow so the
        # head's per-holder counts keep the object alive.
        return (_reconstruct_ref, (self.id, self.owner, self._call_site))

    # Allow `await ref` inside async actors.
    def __await__(self):
        from . import runtime

        result = yield from runtime.get_runtime().get_async(self).__await__()
        return result

    def future(self):
        """Return a concurrent.futures.Future resolving to the value."""
        from . import runtime

        return runtime.get_runtime().as_future(self)
