"""ObjectRef: a future-like handle to an object in the cluster.

Equivalent of the reference's ObjectRef (ref: python/ray/_raylet.pyx ObjectRef
cdef class; ownership semantics per src/ray/core_worker/reference_count.h:61 —
every ref carries its owner's identity so borrowers can locate the value and
report their references)."""
from __future__ import annotations

from typing import Optional

from .ids import ObjectId, TaskId, WorkerId


class ObjectRef:
    __slots__ = ("id", "owner", "_call_site", "__weakref__")

    def __init__(self, object_id: ObjectId, owner: Optional[WorkerId] = None,
                 call_site: str = ""):
        self.id = object_id
        self.owner = owner
        self._call_site = call_site

    def hex(self) -> str:
        return self.id.hex()

    def binary(self) -> bytes:
        return self.id.binary()

    def task_id(self) -> bytes:
        return self.id.task_prefix()

    def __hash__(self):
        return hash(self.id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other.id == self.id

    def __repr__(self):
        return f"ObjectRef({self.id.hex()})"

    def __reduce__(self):
        # Serialization of a ref hands out a *borrowed* reference; the runtime
        # tracks contained refs at serialize() time (serialization.py).
        return (ObjectRef, (self.id, self.owner, self._call_site))

    # Allow `await ref` inside async actors.
    def __await__(self):
        from . import runtime

        result = yield from runtime.get_runtime().get_async(self).__await__()
        return result

    def future(self):
        """Return a concurrent.futures.Future resolving to the value."""
        from . import runtime

        return runtime.get_runtime().as_future(self)
