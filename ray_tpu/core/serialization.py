"""Serialization: cloudpickle with pickle-5 out-of-band buffers.

Equivalent of the reference's serialization context
(ref: python/ray/_private/serialization.py — pickle5 + out-of-band buffers so
large numpy/arrow payloads are written once into the object store without an
extra copy; ObjectRefs found inside values are tracked for the borrowing
protocol).

Wire format of a sealed object:
    [u32 meta_len][meta pickle][u32 nbuf][u64 len_i]*nbuf [buffer bytes...]
meta is the cloudpickle of the value with PickleBuffer placeholders.
"""
from __future__ import annotations

import pickle
import struct
from typing import Any, List, Tuple

import cloudpickle

_PROTOCOL = 5


class SerializedObject:
    """A serialized value: a small metadata pickle plus zero-copy buffers."""

    __slots__ = ("meta", "buffers", "contained_refs")

    def __init__(self, meta: bytes, buffers: List[memoryview], contained_refs: list):
        self.meta = meta
        self.buffers = buffers
        self.contained_refs = contained_refs

    @property
    def total_bytes(self) -> int:
        return (
            8
            + len(self.meta)
            + 8 * len(self.buffers)
            + sum(b.nbytes for b in self.buffers)
        )

    def write_into(self, dest: memoryview) -> int:
        off = 0
        struct.pack_into("<I", dest, off, len(self.meta))
        off += 4
        dest[off : off + len(self.meta)] = self.meta
        off += len(self.meta)
        struct.pack_into("<I", dest, off, len(self.buffers))
        off += 4
        for b in self.buffers:
            struct.pack_into("<Q", dest, off, b.nbytes)
            off += 8
        for b in self.buffers:
            n = b.nbytes
            dest[off : off + n] = b.cast("B")
            off += n
        return off

    def to_bytes(self) -> bytes:
        out = bytearray(self.total_bytes)
        self.write_into(memoryview(out))
        return bytes(out)


class _RefTrackingPickler(cloudpickle.CloudPickler):
    """Tracks ObjectRefs serialized inside the value (borrowing protocol
    hook). Defined once at module level — building a class object per
    serialize() call cost ~15us on the task hot path."""

    def __init__(self, f, contained_refs, **kw):
        super().__init__(f, **kw)
        self._contained_refs = contained_refs

    def persistent_id(self, obj):  # noqa: N802
        return None

    def reducer_override(self, obj):
        from .object_ref import ObjectRef  # local import to avoid cycle

        if isinstance(obj, ObjectRef):
            self._contained_refs.append(obj)
        sup = super()
        return sup.reducer_override(obj) \
            if hasattr(sup, "reducer_override") else NotImplemented


def serialize(value: Any) -> SerializedObject:
    import io

    buffers: List[pickle.PickleBuffer] = []
    contained_refs: list = []
    f = io.BytesIO()
    p = _RefTrackingPickler(f, contained_refs, protocol=_PROTOCOL,
                            buffer_callback=buffers.append)
    p.dump(value)
    views = [b.raw() for b in buffers]
    return SerializedObject(f.getvalue(), views, contained_refs)


def deserialize(data: memoryview | bytes) -> Any:
    mv = memoryview(data)
    off = 0
    (meta_len,) = struct.unpack_from("<I", mv, off)
    off += 4
    meta = mv[off : off + meta_len]
    off += meta_len
    (nbuf,) = struct.unpack_from("<I", mv, off)
    off += 4
    lens = []
    for _ in range(nbuf):
        (n,) = struct.unpack_from("<Q", mv, off)
        off += 8
        lens.append(n)
    bufs = []
    for n in lens:
        bufs.append(mv[off : off + n])
        off += n
    return pickle.loads(bytes(meta), buffers=bufs)


def dumps(value: Any) -> bytes:
    """Serialize to a single contiguous byte string (inline path)."""
    return serialize(value).to_bytes()


def loads(data: bytes | memoryview) -> Any:
    return deserialize(data)
