"""Node manager — the per-node data/scheduling plane (raylet equivalent).

Equivalent of the reference's raylet (ref: src/ray/raylet/node_manager.h:119;
worker_pool.h:156 pop-or-start leasing; local_task_manager.cc:57 dispatch;
placement_group_resource_manager.cc for the 2PC bundle ledger). One Node owns:
a shared-memory PlasmaStore, a pool of worker subprocesses reached over a
Unix-socket RpcChannel each, a FIFO lease queue with resource accounting, and
the placement-group bundle reservations.

Multiple Node objects can live in one driver process — the in-process
multi-node cluster used by tests, mirroring the reference's
``ray.cluster_utils.Cluster`` (python/ray/cluster_utils.py:99). A remote host
would run the same Node served over TCP; the channel protocol is
transport-agnostic.
"""
from __future__ import annotations

import os
import subprocess
import sys
import threading
import time
from collections import deque
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..devtools.locks import instrumented_lock
from ..exceptions import WorkerCrashedError
from .config import Config
from .gcs import NodeInfo
from .ids import ActorId, NodeId, PlacementGroupId, TaskId, WorkerId
from .object_store import make_store
from .resources import ResourceSet, normalize, res_add, res_ge, res_sub
from .rpc import RpcChannel, RpcServer, cluster_token
from .task_spec import TaskSpec, TaskType


@dataclass
class WorkerHandle:
    worker_id: WorkerId
    proc: subprocess.Popen
    channel: Optional[RpcChannel] = None
    state: str = "starting"  # starting | idle | leased | actor | dead
    pid: int = 0
    actor_id: Optional[ActorId] = None
    in_flight: Dict[TaskId, TaskSpec] = field(default_factory=dict)
    lease_resources: ResourceSet = field(default_factory=dict)
    lease_pg: Optional[tuple] = None  # (pg_id, bundle_index)
    # >0 while the worker sits in blocking get/wait calls: its lease
    # resources are returned to the pool so dependent tasks can run (ref:
    # local_task_manager.cc blocked-worker accounting via
    # NotifyDirectCallTaskBlocked/Unblocked). A depth counter, not a bool:
    # threaded actors (max_concurrency>1) can block on several calls at once.
    blocked_depth: int = 0
    # runtime_env dedication (ref: worker_pool.cc keys PopWorker by the
    # env hash): None = fresh/unbound; "" = bound to the plain env;
    # other = bound to that packaged runtime_env for life
    env_hash: Optional[str] = None
    idle_since: float = 0.0  # monotonic timestamp of the last idle entry
    started_at: float = 0.0  # monotonic launch time (launch-strike gate)
    # peer-facing direct-call socket this worker listens on (direct
    # dispatch; resolve_actor hands it to callers — docs/DISPATCH.md)
    direct_addr: Optional[str] = None


@dataclass
class _LeaseRequest:
    spec: TaskSpec
    demand: ResourceSet
    future: Future  # resolves to WorkerHandle
    pg: Optional[tuple] = None  # (pg_id, bundle_index)
    env_hash: str = ""  # runtime_env dedication key ("" = plain)


@dataclass
class _Bundle:
    reserved: ResourceSet
    used: ResourceSet = field(default_factory=dict)
    committed: bool = False


class Node:
    def __init__(self, runtime, node_id: NodeId, resources: ResourceSet,
                 session_dir: str, config: Config,
                 labels: Optional[Dict[str, str]] = None):
        self.runtime = runtime
        self.node_id = node_id
        self.config = config
        self.total_resources = normalize(resources)
        self.available = dict(self.total_resources)
        self.labels = labels or {}
        self.session_dir = session_dir
        self.store = make_store(
            node_id,
            capacity_bytes=int(resources.get("object_store_memory",
                                             config.object_store_memory)),
            spill_dir=(f"{config.object_spilling_dir}/{node_id.hex()[:8]}"
                       if "://" in str(config.object_spilling_dir)
                       else os.path.join(config.object_spilling_dir,
                                         node_id.hex()[:8])),
            min_spilling_size=int(config.min_spilling_size),
        )
        self.total_resources.pop("object_store_memory", None)
        self.available.pop("object_store_memory", None)
        self._lock = instrumented_lock("node", reentrant=True)
        self._workers: Dict[WorkerId, WorkerHandle] = {}
        self._idle: deque = deque()
        # lease backlog bucketed by (demand, pg, env) signature: a burst
        # of identical tasks is ONE bucket, so dispatch is O(#buckets)
        # per event instead of O(backlog) — the 10k-queued envelope's
        # second O(queue^2) cliff after the round-4 early-exit fix
        # (ref: local_task_manager.cc tasks_to_dispatch_ per-class map)
        self._lease_queue: Dict[tuple, deque] = {}
        self._bundles: Dict[tuple, _Bundle] = {}  # (pg_id, idx) -> bundle
        self._starting_count = 0
        self.alive = True
        self.draining = False  # preemption-noticed: no NEW work lands here
        self._sock_path = os.path.join(session_dir, f"node_{node_id.hex()[:12]}.sock")
        self._server = RpcServer(self._sock_path, self._make_handler,
                                 num_handler_threads=int(
                                     self.config.node_server_threads),
                                 family="AF_UNIX")
        self._max_workers = max(int(config.num_workers_soft_limit),
                                int(self.total_resources.get("CPU", 1)))
        self._prefetch_depth = max(1, int(config.worker_task_prefetch))
        # env_hash -> consecutive died-before-register count (reset on a
        # successful register; see _note_launch_failure)
        self._launch_failures: Dict[str, int] = {}
        for _ in range(int(config.worker_prestart_count)):
            self._start_worker()
        # idle-worker reclamation (ref: worker_pool.cc idle worker killing;
        # config.worker_idle_timeout_s existed but was unenforced until r3)
        threading.Thread(target=self._idle_reaper_loop, daemon=True,
                         name="idle-reaper").start()

    def _idle_reaper_loop(self) -> None:
        timeout = float(self.config.worker_idle_timeout_s)
        keep = int(self.config.worker_prestart_count)
        while self.alive:
            time.sleep(min(30.0, max(1.0, timeout / 4)))
            now = time.monotonic()
            victims = []
            with self._lock:
                if not self.alive:
                    return
                idle = [w for w in self._workers.values()
                        if w.state == "idle"]
                reclaimable = sorted(idle, key=lambda w: w.idle_since)
                # oldest first, but always keep the prestart floor warm
                for w in reclaimable[:max(0, len(idle) - keep)]:
                    if now - w.idle_since > timeout:
                        victims.append(w)
                for w in victims:
                    self._terminate_worker(w)
                if victims:
                    self._idle = deque(x for x in self._idle
                                       if x.state == "idle")

    def info(self) -> NodeInfo:
        return NodeInfo(node_id=self.node_id, total_resources=dict(self.total_resources),
                        labels=dict(self.labels), alive=self.alive)

    # ---- leasing (ref: worker_pool.h PopWorker + local_task_manager.cc) ------

    def request_lease(self, spec: TaskSpec) -> Future:
        fut: Future = Future()
        # submitters on the hot path pre-normalize (remote_function);
        # decoded/foreign specs fall through to normalize here
        demand = spec.__dict__.get("_demand")
        if demand is None:
            demand = normalize(spec.resources)
        pg = None
        strat = spec.scheduling_strategy
        if strat.kind == "PLACEMENT_GROUP" and strat.placement_group_id is not None:
            pg = self._pick_bundle(strat.placement_group_id, strat.bundle_index, demand)
            if pg is None:
                fut.set_exception(WorkerCrashedError(
                    f"No bundle with capacity for {demand} in pg "
                    f"{strat.placement_group_id.hex()[:8]} on this node"))
                return fut
        from .runtime_env import env_hash as _env_hash

        req = _LeaseRequest(spec=spec, demand=demand, future=fut, pg=pg,
                            env_hash=_env_hash(spec.runtime_env))
        dkey = spec.__dict__.get("_demand_key")
        if dkey is None:
            dkey = tuple(sorted(demand.items()))
        # task type is part of the signature: lease reuse must never hand
        # a busy task worker to an actor-creation request (push_task
        # would flip it to state="actor" mid-stream)
        sig = (dkey, req.pg, req.env_hash, spec.task_type)
        with self._lock:
            self._lease_queue.setdefault(sig, deque()).append(req)
        self._dispatch()
        return fut

    def steal_queued_leases(self, everything: bool = False) -> list:
        """Remove and return queued (not yet granted) NON-placement-group
        lease requests so the runtime can re-route them — the spillback
        half of elastic capacity (docs/FAULT_TOLERANCE.md "Elasticity").

        Default: steal only buckets this node cannot grant from its
        CURRENT availability (a request parked behind a full node, which
        a freshly joined node could serve right now). ``everything``
        steals every queued non-PG request — the draining path, where
        this node must not start new work at all. PG-bundle leases stay:
        their bundle reservation pins them here by construction.

        A stolen request's future is simply abandoned (nothing holds it
        once it leaves the queue — its grant callback never fires); the
        caller re-enters the TaskSpec through the scheduler."""
        stolen = []
        with self._lock:
            if not self.alive:
                return []
            for sig in list(self._lease_queue.keys()):
                dkey, pg, _env, _ttype = sig
                if pg is not None:
                    continue
                if not everything and res_ge(self.available, dict(dkey)):
                    continue  # grantable here as soon as a worker frees
                bucket = self._lease_queue[sig]
                reqs = [r for r in bucket if not r.future.cancelled()]
                if reqs:
                    stolen.extend(reqs)
                del self._lease_queue[sig]
        return stolen

    def _pick_bundle(self, pg_id: PlacementGroupId, index: int,
                     demand: ResourceSet) -> Optional[tuple]:
        with self._lock:
            if index >= 0:
                key = (pg_id, index)
                b = self._bundles.get(key)
                if b is not None and b.committed:
                    return key
                return None
            for key, b in sorted(self._bundles.items(), key=lambda kv: kv[0][1]):
                if key[0] == pg_id and b.committed and res_ge(
                        res_sub(b.reserved, b.used), demand):
                    return key
        return None

    def _dispatch(self) -> None:
        """Grant queued leases that fit; start workers on demand.

        Per-bucket scan: every request in a bucket shares one (demand,
        pg, env) signature, so the first head that can't be granted ends
        that bucket — no per-request walk of the backlog."""
        grants = []
        failures = []
        with self._lock:
            if not self.alive:
                return
            for sig in list(self._lease_queue.keys()):
                bucket = self._lease_queue[sig]
                while bucket:
                    req = bucket[0]
                    if req.future.cancelled():
                        bucket.popleft()
                        continue
                    if not self._fits(req):
                        break  # same demand behind it: none of it fits
                    cont = ((req.spec.runtime_env or {}).get("container")
                            if req.env_hash else None)
                    # container envs need a worker LAUNCHED inside the
                    # container — a fresh host worker can't be moved in
                    worker = self._pop_idle(req.env_hash,
                                            dedicated_only=cont is not None)
                    if worker is None:
                        # blocked workers don't count toward the cap:
                        # each freed its resources and waits on work that
                        # may only be runnable by a new worker
                        active = (len(self._workers) + self._starting_count
                                  - sum(1 for w in self._workers.values()
                                        if w.blocked_depth > 0))
                        if active >= self._max_workers:
                            # cap reached but an idle worker bound to a
                            # DIFFERENT runtime_env may be the blocker:
                            # evict one to make room (ref: worker_pool.cc
                            # idle-worker kill under pressure). A
                            # container request can't use unbound
                            # workers either — they count as evictable
                            # for it, or it would starve behind a warm
                            # pool of plain idle workers.
                            victim = next(
                                (w for w in self._idle
                                 if w.state == "idle"
                                 and w.env_hash != req.env_hash
                                 and (w.env_hash is not None
                                      or cont is not None)), None)
                            if victim is not None:
                                self._terminate_worker(victim)
                                self._idle = deque(
                                    x for x in self._idle
                                    if x is not victim)
                                active -= 1
                        if active < self._max_workers or not self._workers:
                            try:
                                self._start_worker(
                                    container=cont,
                                    env_hash=req.env_hash if cont else None)
                            except OSError as e:
                                # launcher missing/unexecutable: fail THIS
                                # request with a clear error instead of
                                # tearing down dispatch for everyone
                                # (future resolved outside the lock, like
                                # grants — callbacks may re-enter)
                                bucket.popleft()
                                failures.append((req, WorkerCrashedError(
                                    "container worker launch failed ("
                                    f"{self.config.container_launcher}): "
                                    f"{e}")))
                                continue
                        break  # this bucket needs a worker that isn't
                        # here yet; other buckets (different env) may
                        # still have one
                    bucket.popleft()
                    self._take_resources(req)
                    worker.env_hash = req.env_hash  # dedicate on grant
                    worker.state = "leased"
                    worker.lease_resources = req.demand
                    worker.lease_pg = req.pg
                    grants.append((req, worker))
                if not bucket:
                    del self._lease_queue[sig]
        for req, worker in grants:
            req.future.set_result(worker)
        for req, err in failures:
            if not req.future.done():
                req.future.set_exception(err)

    def _fits(self, req: _LeaseRequest) -> bool:
        if req.pg is not None:
            b = self._bundles.get(req.pg)
            return b is not None and res_ge(res_sub(b.reserved, b.used), req.demand)
        return res_ge(self.available, req.demand)

    def _take_resources(self, req: _LeaseRequest) -> None:
        if req.pg is not None:
            b = self._bundles[req.pg]
            b.used = res_add(b.used, req.demand)
        else:
            self.available = res_sub(self.available, req.demand)

    def release_lease(self, worker: WorkerHandle, terminate: bool = False) -> None:
        with self._lock:
            if worker.blocked_depth > 0:
                worker.blocked_depth = 0  # resources already back in the pool
            elif worker.lease_pg is not None:
                b = self._bundles.get(worker.lease_pg)
                if b is not None:
                    b.used = res_sub(b.used, worker.lease_resources)
            else:
                self.available = res_add(self.available, worker.lease_resources)
            worker.lease_resources = {}
            worker.lease_pg = None
            if worker.state in ("leased", "actor") and not terminate:
                worker.state = "idle"
                worker.idle_since = time.monotonic()
                self._idle.append(worker)
            elif terminate:
                self._terminate_worker(worker)
        self._dispatch()

    def notify_worker_blocked(self, worker: WorkerHandle) -> None:
        """The worker entered a blocking get/wait: return its lease resources
        to the pool so tasks it depends on can be dispatched here. Without
        this, nested task graphs deadlock once every CPU is held by a blocked
        parent (ref: local_task_manager.cc:57 blocked-worker accounting)."""
        with self._lock:
            if not worker.lease_resources \
                    or worker.state not in ("leased", "actor"):
                return
            worker.blocked_depth += 1
            if worker.blocked_depth > 1:
                return  # resources already released by the first blocker
            if worker.lease_pg is not None:
                b = self._bundles.get(worker.lease_pg)
                if b is not None:
                    b.used = res_sub(b.used, worker.lease_resources)
            else:
                self.available = res_add(self.available, worker.lease_resources)
        self._dispatch()

    def notify_worker_unblocked(self, worker: WorkerHandle) -> None:
        """The blocking call returned: re-take the lease resources. May drive
        availability negative (temporary oversubscription) — progress beats
        strictness here, exactly as the reference behaves on unblock."""
        with self._lock:
            if worker.blocked_depth == 0:
                return
            worker.blocked_depth -= 1
            if worker.blocked_depth > 0:
                return  # other calls from this worker still blocked
            if worker.lease_pg is not None:
                b = self._bundles.get(worker.lease_pg)
                if b is not None:
                    b.used = res_add(b.used, worker.lease_resources)
            else:
                self.available = res_sub(self.available, worker.lease_resources)

    def _worker_alive(self, w: WorkerHandle) -> bool:
        return w.channel is not None and not w.channel.closed

    def _pop_idle(self, env_hash: str = "",
                  dedicated_only: bool = False) -> Optional[WorkerHandle]:
        """Pop an idle worker compatible with the request's runtime_env:
        one already dedicated to the same env, or a fresh unbound one (it
        gets dedicated on grant). A worker bound to a DIFFERENT env is
        never reused — its process state (env vars, sys.path, cwd) is that
        environment's (ref: worker_pool.cc runtime-env-keyed pop).
        RemoteNode shares this loop and overrides only _worker_alive
        (remote workers have no head-side channel object)."""
        kept = []
        found = None
        while self._idle:
            w = self._idle.popleft()
            if w.state != "idle" or not self._worker_alive(w):
                continue
            if w.env_hash == env_hash or (w.env_hash is None
                                          and not dedicated_only):
                found = w
                break
            kept.append(w)
        self._idle.extendleft(reversed(kept))
        return found

    # ---- worker lifecycle ----------------------------------------------------

    def _start_worker(self, container: Optional[dict] = None,
                      env_hash: Optional[str] = None) -> WorkerHandle:
        worker_id = WorkerId.from_random()
        env = dict(os.environ)
        env["PYTHONPATH"] = os.pathsep.join(p for p in sys.path if p)
        # auth token travels via env (RTPU_AUTHKEY), never argv — argv is
        # world-readable through /proc/<pid>/cmdline
        env["RTPU_AUTHKEY"] = cluster_token().hex()
        # -S skips site processing (a sitecustomize importing jax costs ~2s
        # per worker start); the parent's sys.path travels via PYTHONPATH.
        cmd = [
            sys.executable, "-S", "-m", "ray_tpu.core.worker_main",
            "--address", self._sock_path,
            "--worker-id", worker_id.hex(),
            "--node-id", self.node_id.hex(),
        ]
        if container is not None:
            # containerized worker (ref: runtime_env/container.py)
            from .runtime_env import container_command

            cmd = container_command(self.config.container_launcher,
                                    container, cmd)
        proc = subprocess.Popen(cmd, env=env)
        handle = WorkerHandle(worker_id=worker_id, proc=proc, pid=proc.pid,
                              started_at=time.monotonic())
        if env_hash is not None:
            handle.env_hash = env_hash  # container workers: dedicated
            # from birth (the env can't be applied to a host process)
        with self._lock:  # reentrant: callers may already hold it
            self._workers[worker_id] = handle
            self._starting_count += 1
        # watchdog: a worker that dies before registering must not strand the
        # lease queue (ref: worker_pool.cc PopWorker failure callbacks)
        threading.Thread(target=self._reap_worker, args=(handle,), daemon=True,
                         name="worker-reaper").start()
        return handle

    def _reap_worker(self, handle: WorkerHandle) -> None:
        try:
            handle.proc.wait()
        except Exception:
            return
        with self._lock:
            if handle.state == "starting":
                self._starting_count = max(0, self._starting_count - 1)
        self._on_worker_exit(handle)

    def _on_register(self, channel: RpcChannel, payload: dict) -> None:
        worker_id: WorkerId = payload["worker_id"]
        with self._lock:
            handle = self._workers.get(worker_id)
            if handle is None:
                handle = WorkerHandle(worker_id=worker_id, proc=None,  # type: ignore
                                      pid=payload.get("pid", 0))
                self._workers[worker_id] = handle
            handle.channel = channel
            handle.pid = payload.get("pid", handle.pid)
            handle.direct_addr = payload.get("direct_addr")
            handle.state = "idle"
            self._launch_failures.pop(handle.env_hash or "", None)
            handle.idle_since = time.monotonic()
            self._starting_count = max(0, self._starting_count - 1)
            self._idle.append(handle)
        channel.on_close(lambda: self._on_worker_exit(handle))
        self._dispatch()

    def _on_worker_exit(self, worker: WorkerHandle) -> None:
        with self._lock:
            if worker.state == "dead":
                return
            was_starting = worker.state == "starting"
            worker.state = "dead"
            self._workers.pop(worker.worker_id, None)
            if worker.blocked_depth > 0:
                worker.blocked_depth = 0  # resources already back in the pool
            elif worker.lease_resources:
                if worker.lease_pg is not None:
                    b = self._bundles.get(worker.lease_pg)
                    if b is not None:
                        b.used = res_sub(b.used, worker.lease_resources)
                else:
                    self.available = res_add(self.available, worker.lease_resources)
            in_flight = list(worker.in_flight.values())
            actor_id = worker.actor_id
        for spec in in_flight:
            self.runtime.on_worker_crashed(spec, self.node_id)
        # drop every object reference the dead worker held
        self.runtime.refcount.release_holder(worker.worker_id)
        if actor_id is not None and self.alive:
            self.runtime.gcs.on_actor_failure(
                actor_id, f"worker {worker.worker_id.hex()[:8]} died")
        if was_starting and self.alive:
            # died before registering: a broken launch recipe (bad
            # container launcher, image pull failure) would otherwise
            # loop start->die->restart forever. Quick deaths (<30s) trip
            # the breaker at 3 consecutive strikes; slow deaths (a
            # loaded box can stall registration) still count but only
            # trip at 6 — slow-but-broken recipes (registry timeouts)
            # must fail eventually too, just with more patience.
            fast = bool(worker.started_at) and \
                time.monotonic() - worker.started_at < 30.0
            self._note_launch_failure(worker.env_hash or "", fast)
        self._dispatch()

    _LAUNCH_STRIKES = 3
    _LAUNCH_STRIKES_SLOW = 6

    def _note_launch_failure(self, env_hash: str,
                             fast: bool = True) -> None:
        to_fail: list = []
        with self._lock:
            n = self._launch_failures.get(env_hash, 0) + 1
            self._launch_failures[env_hash] = n
            limit = (self._LAUNCH_STRIKES if fast
                     else self._LAUNCH_STRIKES_SLOW)
            if n < limit:
                return
            self._launch_failures[env_hash] = 0
            for sig in list(self._lease_queue.keys()):
                if sig[2] == env_hash:
                    to_fail.extend(self._lease_queue.pop(sig))
        for req in to_fail:
            if not req.future.done():
                req.future.set_exception(WorkerCrashedError(
                    f"workers for runtime_env {env_hash or '<plain>'} "
                    f"exited before registering {self._LAUNCH_STRIKES} "
                    f"times in a row on node {self.node_id.hex()[:8]} — "
                    f"check the worker launch recipe (container "
                    f"launcher / image) and worker logs"))

    def _terminate_worker(self, worker: WorkerHandle) -> None:
        # kill_worker/shutdown call in unlocked: the pop must not race a
        # dispatch pass iterating _workers under the (reentrant) lock
        with self._lock:
            worker.state = "dead"
            self._workers.pop(worker.worker_id, None)
        self.runtime.refcount.release_holder(worker.worker_id)
        if worker.channel is not None:
            worker.channel.notify("shutdown")
            worker.channel.close()
        if worker.proc is not None:
            try:
                worker.proc.terminate()
            except Exception:
                pass

    # ---- task push (direct transport) ----------------------------------------

    def push_task(self, worker: WorkerHandle, spec: TaskSpec) -> None:
        """Push a task to a leased worker (ref: direct_task_transport.h:211
        PushNormalTask — the raylet is off the data path)."""
        with self._lock:
            worker.in_flight[spec.task_id] = spec
            if spec.task_type == TaskType.ACTOR_CREATION_TASK:
                worker.state = "actor"
                worker.actor_id = spec.actor_id
        if worker.channel is None or worker.channel.closed:
            self._on_worker_exit(worker)
            return
        worker.channel.notify("push_task", spec)

    def on_task_done(self, worker: WorkerHandle, payload: dict) -> None:
        task_id: TaskId = payload["task_id"]
        with self._lock:
            spec = worker.in_flight.pop(task_id, None)
        if spec is None:
            return
        self.runtime.on_task_done(spec, payload, self.node_id, worker)
        if spec.task_type == TaskType.NORMAL_TASK:
            nxt = self._reuse_lease(worker)
            if nxt:
                # lease reuse (ref: direct_task_transport lease caching /
                # local_task_manager same-scheduling-class dispatch): the
                # next queued requests have the identical (demand, pg,
                # env) signature, so the worker flows straight to them —
                # no resource return, no dispatch scan, no new grant.
                # Up to `prefetch` tasks ride one lease (executed
                # sequentially by the worker; only the lease's own
                # resources are held), which keeps the worker fed and
                # lets both channel directions coalesce frames.
                for req in nxt:
                    req.future.set_result(worker)
            elif not worker.in_flight:
                self.release_lease(worker)

    def _reuse_lease(self, worker: WorkerHandle) -> list:
        out: list = []
        with self._lock:
            if not self.alive or worker.state != "leased" \
                    or worker.channel is None or worker.channel.closed:
                return out
            want = self._prefetch_depth - len(worker.in_flight)
            if want <= 0:
                return out
            sig = (tuple(sorted(worker.lease_resources.items())),
                   worker.lease_pg, worker.env_hash or "",
                   TaskType.NORMAL_TASK)  # reuse serves normal tasks only
            bucket = self._lease_queue.get(sig)
            while bucket and len(out) < want:
                req = bucket.popleft()
                if not bucket:
                    del self._lease_queue[sig]
                    bucket = None
                if not req.future.cancelled():
                    out.append(req)
        return out

    # ---- placement group bundles: 2PC ----------------------------------------
    # (ref: node_manager.proto:380-384 PrepareBundleResources/CommitBundleResources)

    def prepare_bundle(self, pg_id: PlacementGroupId, index: int,
                       resources: ResourceSet) -> bool:
        with self._lock:
            demand = normalize(resources)
            if not res_ge(self.available, demand):
                return False
            self.available = res_sub(self.available, demand)
            self._bundles[(pg_id, index)] = _Bundle(reserved=demand)
            return True

    def commit_bundle(self, pg_id: PlacementGroupId, index: int) -> None:
        with self._lock:
            b = self._bundles.get((pg_id, index))
            if b is not None:
                b.committed = True
        self._dispatch()

    def return_bundle(self, pg_id: PlacementGroupId, index: int) -> None:
        with self._lock:
            b = self._bundles.pop((pg_id, index), None)
            if b is not None:
                self.available = res_add(self.available, b.reserved)
        self._dispatch()

    # ---- worker RPC handler --------------------------------------------------

    def _make_handler(self, channel: RpcChannel):
        state = {"worker": None}

        def handler(method: str, payload):
            if method == "register":
                self._on_register(channel, payload)
                with self._lock:
                    state["worker"] = self._workers.get(payload["worker_id"])
                # local workers tee stdout/stderr too when the head keeps
                # a log store (dashboard log view); lines still reach the
                # console through the tee's original stream
                return {"forward_logs":
                        bool(int(self.config.capture_worker_logs))}
            worker: Optional[WorkerHandle] = state["worker"]
            if method == "task_done":
                if worker is not None:
                    self.on_task_done(worker, payload)
                return None
            if method == "direct_result":
                # a worker finished one of the DRIVER's direct calls
                # (submitted over this same channel); hot path — handled
                # before the generic worker-call chain
                self.runtime.on_direct_result(payload)
                return None
            if method == "create_object":
                return self.store.create(payload["object_id"], payload["size"])
            if method == "seal_object":
                self.store.seal(payload["object_id"])
                self.store.pin(payload["object_id"])
                self.runtime.on_object_sealed(
                    payload["object_id"], self.node_id,
                    size=self.store.object_size(payload["object_id"]))
                if worker is not None and payload.get("is_put"):
                    # a worker ray_tpu.put: the worker holds the only ref
                    # (its adopt_owned_ref finalizer sends the balancing
                    # remove). Task returns sealed via _report_success get
                    # their lifetime from the caller's returned refs.
                    self.runtime.refcount.add_holder_ref(
                        payload["object_id"], worker.worker_id)
                return True
            # everything else is the shared core-worker API, served by the runtime
            return self.runtime.handle_worker_call(self, worker, method, payload)

        return handler

    # ---- queries & lifecycle -------------------------------------------------

    def get_worker(self, worker_id: WorkerId) -> Optional[WorkerHandle]:
        with self._lock:
            return self._workers.get(worker_id)

    def list_workers(self) -> List[WorkerHandle]:
        with self._lock:
            return list(self._workers.values())

    # ---- on-demand introspection (ref: `ray stack` per-node fan-out) ---------

    def worker_stack(self, worker: WorkerHandle,
                     timeout: float = 5.0) -> dict:
        """One worker's thread stacks, served by its dump_stacks RPC
        (answered from the worker's handler pool — works while the
        executor thread is blocked in user code or get())."""
        if worker.channel is None or worker.channel.closed:
            raise RuntimeError("worker has no live channel")
        return worker.channel.call("dump_stacks", None, timeout=timeout)

    def worker_profile(self, worker: WorkerHandle, duration_s: float = 5.0,
                       interval_s: float = 0.01) -> dict:
        """On-demand sampling profile of one worker (start/stop happens
        worker-side; the call returns the aggregated result)."""
        if worker.channel is None or worker.channel.closed:
            raise RuntimeError("worker has no live channel")
        return worker.channel.call(
            "profile", {"duration_s": float(duration_s),
                        "interval_s": float(interval_s)},
            timeout=float(duration_s) + 30.0)

    # ---- compiled-graph control plane (ray_tpu/cgraph) -----------------------

    def worker_notify(self, worker: WorkerHandle, method: str,
                      payload) -> None:
        """Fire-and-forget message to one worker (cgraph envelope
        delivery); RemoteNode overrides with the agent relay. Raises
        when the channel is provably gone — a silently-dropped envelope
        would strand the consumer waiting on a seq that never arrives,
        while raising lets the sender's retraction/abort paths run."""
        if worker.channel is None or worker.channel.closed:
            raise RuntimeError(
                f"worker {worker.worker_id.hex()[:8]} has no live channel")
        worker.channel.notify(method, payload)

    def worker_cgraph_call(self, worker: WorkerHandle, method: str,
                           payload, timeout: float = 30.0):
        """Request/response to one worker (cgraph_load / cgraph_stop)."""
        if worker.channel is None or worker.channel.closed:
            raise RuntimeError("worker has no live channel")
        return worker.channel.call(method, payload, timeout=timeout)

    def num_workers(self) -> int:
        with self._lock:
            return len(self._workers)

    def queue_len(self) -> int:
        with self._lock:
            return sum(len(b) for b in self._lease_queue.values())

    def kill_worker(self, worker: WorkerHandle, force: bool = True) -> None:
        try:
            if force and worker.proc is not None:
                worker.proc.kill()
            else:
                self._terminate_worker(worker)
        except Exception:
            pass

    def shutdown(self, kill: bool = False) -> None:
        """Graceful stop, or simulated node failure when kill=True."""
        with self._lock:
            if not self.alive:
                return
            self.alive = False
            workers = list(self._workers.values())
            queued = [r for b in self._lease_queue.values() for r in b]
            self._lease_queue.clear()
        for req in queued:
            if not req.future.done():
                req.future.set_exception(
                    WorkerCrashedError(f"node {self.node_id.hex()[:8]} shut down"))
        for w in workers:
            try:
                if kill:
                    if w.proc is not None:
                        w.proc.kill()
                else:
                    self._terminate_worker(w)
            except Exception:
                pass
        if kill:
            self.store.destroy()
        self._server.close()
        for w in workers:
            if w.proc is not None:
                try:
                    w.proc.wait(timeout=5)
                except Exception:
                    pass
        if not kill:
            self.store.destroy()
