"""Typed wire codec for cross-process control frames.

Replaces pickle on the RPC channels (rpc.py). The reference's control
plane is protobuf/gRPC end-to-end (ref: src/ray/protobuf/common.proto,
src/ray/rpc/grpc_server.h); `multiprocessing.connection`'s default pickle
framing meant anyone who could reach the head port with the cluster token
got arbitrary code execution on every node. This codec is structural: it
can ONLY produce the primitive types and the explicitly registered
control-plane structs below. A malformed or malicious frame raises
`WireDecodeError` at the framing layer — it is never evaluated.

User payloads (function blobs, serialized task args/results) remain
cloudpickle — but as opaque `bytes` inside frames; they are only
deserialized inside the worker that executes the user's code, which is the
boundary the reference draws too.

Format (version 1): 2-byte magic "RW", 1-byte version, then one encoded
value. Values are tag-prefixed: primitives carry fixed/length-prefixed
encodings; containers carry a u32 count; registered structs carry a u16
struct id and their registry-ordered field tuple.
"""
from __future__ import annotations

import struct
from enum import Enum
from typing import Any, Callable, Dict, Optional, Tuple

MAGIC = b"RW"
VERSION = 1

_T_NONE = 0
_T_TRUE = 1
_T_FALSE = 2
_T_INT = 3       # int64
_T_BIGINT = 4    # arbitrary precision, length-prefixed two's complement
_T_FLOAT = 5
_T_STR = 6
_T_BYTES = 7
_T_LIST = 8
_T_TUPLE = 9
_T_DICT = 10
_T_SET = 11
_T_STRUCT = 12
_T_FROZENSET = 13

_I64_MIN, _I64_MAX = -(1 << 63), (1 << 63) - 1

# pre-compiled packers: struct.pack with a literal fmt re-parses the fmt
# string per call; these are the per-field hot path of every frame
_PACK_Q = struct.Struct("<q").pack
_PACK_I = struct.Struct("<I").pack
_PACK_D = struct.Struct("<d").pack
_PACK_H = struct.Struct("<H").pack
_UNPACK_Q = struct.Struct("<q").unpack
_UNPACK_I = struct.Struct("<I").unpack
_UNPACK_D = struct.Struct("<d").unpack
_UNPACK_H = struct.Struct("<H").unpack


class WireEncodeError(TypeError):
    pass


class WireDecodeError(ValueError):
    pass


# struct id -> (cls, encode(obj)->tuple, decode(tuple)->obj)
_BY_ID: Dict[int, Tuple[type, Callable, Callable]] = {}
_BY_CLS: Dict[type, int] = {}


def register_struct(sid: int, cls: type,
                    encode: Optional[Callable] = None,
                    decode: Optional[Callable] = None) -> None:
    """Register a control-plane type. Default encode/decode use dataclass
    field order (positional __init__)."""
    if sid in _BY_ID:
        raise ValueError(f"struct id {sid} already registered")
    if encode is None or decode is None:
        import dataclasses

        names = [f.name for f in dataclasses.fields(cls)]
        encode = encode or (lambda o, _n=tuple(names):
                            tuple(getattr(o, n) for n in _n))
        decode = decode or (lambda vals, _c=cls: _c(*vals))
    _BY_ID[sid] = (cls, encode, decode)
    _BY_CLS[cls] = sid


def _encode_value(buf: bytearray, v: Any) -> None:
    t = type(v)
    if v is None:
        buf.append(_T_NONE)
    elif t is bool:
        buf.append(_T_TRUE if v else _T_FALSE)
    elif t is int:
        if _I64_MIN <= v <= _I64_MAX:
            buf.append(_T_INT)
            buf += _PACK_Q(v)
        else:
            raw = v.to_bytes((v.bit_length() + 8) // 8, "little", signed=True)
            buf.append(_T_BIGINT)
            buf += _PACK_I(len(raw))
            buf += raw
    elif t is float:
        buf.append(_T_FLOAT)
        buf += _PACK_D(v)
    elif t is str:
        raw = v.encode()
        buf.append(_T_STR)
        buf += _PACK_I(len(raw))
        buf += raw
    elif t is bytes or t is bytearray or t is memoryview:
        raw = bytes(v) if t is not bytes else v
        buf.append(_T_BYTES)
        buf += _PACK_I(len(raw))
        buf += raw
    elif t is list:
        buf.append(_T_LIST)
        buf += _PACK_I(len(v))
        for item in v:
            _encode_value(buf, item)
    elif t is tuple:
        buf.append(_T_TUPLE)
        buf += _PACK_I(len(v))
        for item in v:
            _encode_value(buf, item)
    elif t is dict:
        buf.append(_T_DICT)
        buf += _PACK_I(len(v))
        for k, item in v.items():
            _encode_value(buf, k)
            _encode_value(buf, item)
    elif t is set or t is frozenset:
        buf.append(_T_SET if t is set else _T_FROZENSET)
        buf += _PACK_I(len(v))
        for item in v:
            _encode_value(buf, item)
    else:
        sid = _BY_CLS.get(t)
        if sid is None:
            # numpy SCALARS occasionally leak into resource/metric dicts;
            # coerce rather than force every caller to sanitize. Arrays
            # must raise WireEncodeError (a bare ValueError from .item()
            # would tear the channel down instead of dropping the frame)
            if type(v).__module__ == "numpy":
                if getattr(v, "ndim", 1) == 0:
                    _encode_value(buf, v.item())
                    return
                raise WireEncodeError(
                    "numpy arrays don't cross the control plane raw; "
                    "serialize to bytes first")
            if isinstance(v, Enum):
                raise WireEncodeError(
                    f"unregistered enum {t.__name__} on the control plane")
            raise WireEncodeError(
                f"type {t.__module__}.{t.__name__} is not wire-encodable; "
                f"register it in core/wire.py or send it as bytes")
        tmpl = getattr(v, "_wire_tmpl", None)
        if tmpl is not None:
            # template fast path (TaskSpec hot loop): constant fields of
            # a RemoteFunction's specs are pre-encoded once; per call
            # only the varying fields (task_id, args, ...) are walked —
            # ~5 value encodes instead of ~40 per pushed task
            buf.append(_T_STRUCT)
            buf += _PACK_H(sid)
            buf.append(_T_TUPLE)
            buf += _PACK_I(tmpl[0])
            for const, name in tmpl[1]:
                buf += const
                if name is not None:
                    _encode_value(buf, getattr(v, name))
            return
        _, enc, _ = _BY_ID[sid]
        buf.append(_T_STRUCT)
        buf += _PACK_H(sid)
        _encode_value(buf, tuple(enc(v)))


def make_struct_template(obj, varying: tuple) -> tuple:
    """Pre-encode the constant fields of a registered dataclass struct.

    Returns (field_count, ((const_bytes, varying_name_or_None), ...)) for
    the _wire_tmpl fast path in _encode_value. `varying` names are
    re-encoded per call from the live object; every other field is
    frozen to the bytes of its value on `obj` NOW — callers guarantee
    those fields are identical for every object carrying this template
    (RemoteFunction options are fixed at construction, so its specs
    qualify)."""
    import dataclasses

    names = [f.name for f in dataclasses.fields(type(obj))]
    segs = []
    buf = bytearray()
    for name in names:
        if name in varying:
            segs.append((bytes(buf), name))
            buf = bytearray()
        else:
            _encode_value(buf, getattr(obj, name))
    segs.append((bytes(buf), None))
    return (len(names), tuple(segs))


class _Reader:
    __slots__ = ("data", "pos")

    def __init__(self, data: bytes):
        self.data = data
        self.pos = 0

    def take(self, n: int) -> bytes:
        p = self.pos
        if p + n > len(self.data):
            raise WireDecodeError("truncated frame")
        self.pos = p + n
        return self.data[p:p + n]

    def u8(self) -> int:
        return self.take(1)[0]

    def u32(self) -> int:
        return _UNPACK_I(self.take(4))[0]


_MAX_CONTAINER = 1 << 24  # 16M entries: sanity bound against forged counts
_MAX_DEPTH = 100  # a forged deep-nesting frame must not RecursionError
# through the read loop's drop-and-continue (RecursionError is not a
# WireDecodeError and would tear the channel down)


def _decode_value(r: _Reader, depth: int = 0) -> Any:
    if depth > _MAX_DEPTH:
        raise WireDecodeError("frame nesting too deep")
    tag = r.u8()
    if tag == _T_NONE:
        return None
    if tag == _T_TRUE:
        return True
    if tag == _T_FALSE:
        return False
    if tag == _T_INT:
        return _UNPACK_Q(r.take(8))[0]
    if tag == _T_BIGINT:
        return int.from_bytes(r.take(r.u32()), "little", signed=True)
    if tag == _T_FLOAT:
        return _UNPACK_D(r.take(8))[0]
    if tag == _T_STR:
        return r.take(r.u32()).decode()
    if tag == _T_BYTES:
        return r.take(r.u32())
    if tag in (_T_LIST, _T_TUPLE, _T_SET, _T_FROZENSET):
        n = r.u32()
        if n > _MAX_CONTAINER:
            raise WireDecodeError(f"container too large: {n}")
        items = [_decode_value(r, depth + 1) for _ in range(n)]
        if tag == _T_LIST:
            return items
        if tag == _T_TUPLE:
            return tuple(items)
        return set(items) if tag == _T_SET else frozenset(items)
    if tag == _T_DICT:
        n = r.u32()
        if n > _MAX_CONTAINER:
            raise WireDecodeError(f"container too large: {n}")
        return {_decode_value(r, depth + 1): _decode_value(r, depth + 1)
                for _ in range(n)}
    if tag == _T_STRUCT:
        sid = _UNPACK_H(r.take(2))[0]
        entry = _BY_ID.get(sid)
        if entry is None:
            raise WireDecodeError(f"unknown struct id {sid}")
        vals = _decode_value(r, depth + 1)
        if not isinstance(vals, tuple):
            raise WireDecodeError("struct fields must be a tuple")
        _, _, dec = entry
        try:
            return dec(vals)
        except WireDecodeError:
            raise
        except Exception as e:
            raise WireDecodeError(f"bad struct {sid} fields: {e!r}") from e
    raise WireDecodeError(f"unknown tag {tag}")


def encode(obj: Any) -> bytes:
    buf = bytearray(MAGIC)
    buf.append(VERSION)
    try:
        _encode_value(buf, obj)
    except WireEncodeError:
        raise
    except Exception as e:
        # UnicodeEncodeError (surrogate strings), RecursionError (deep
        # payloads), etc. must surface as WireEncodeError: rpc.py's write
        # loop drops the frame for that type but tears the channel down
        # for anything else
        raise WireEncodeError(f"unencodable payload: {e!r}") from e
    return bytes(buf)


def decode_py(data: bytes) -> Any:
    """Pure-Python decoder — the semantics reference and the fallback
    when the C extension can't build."""
    if len(data) < 3 or data[:2] != MAGIC:
        raise WireDecodeError("bad magic: not a ray_tpu control frame")
    if data[2] != VERSION:
        raise WireDecodeError(f"unsupported wire version {data[2]}")
    r = _Reader(data)
    r.pos = 3
    out = _decode_value(r)
    if r.pos != len(data):
        raise WireDecodeError("trailing bytes after frame")
    return out


def _struct_from_wire(sid: int, vals: tuple) -> Any:
    """Registry dispatch for the C decoder (same error contract as the
    _T_STRUCT branch of _decode_value)."""
    entry = _BY_ID.get(sid)
    if entry is None:
        raise WireDecodeError(f"unknown struct id {sid}")
    try:
        return entry[2](vals)
    except WireDecodeError:
        raise
    except Exception as e:
        raise WireDecodeError(f"bad struct {sid} fields: {e!r}") from e


decode = decode_py


def _try_native_decode() -> None:
    """Swap in the C decode path (ray_tpu/native/wirefast.c) when it
    builds; ~5-10x on TaskSpec-shaped frames, bit-compatible by test."""
    global decode
    try:
        from ..native import load_wirefast

        mod = load_wirefast()
    except Exception:
        return
    if mod is None:
        return
    mod.init(WireDecodeError, _struct_from_wire)
    decode = mod.decode


# ---------------------------------------------------------------------------
# control-plane type registry
# ---------------------------------------------------------------------------


def _register_defaults() -> None:
    from . import ids as _ids
    from .gcs import (ActorInfo, ActorState, JobInfo, NodeInfo,
                      PlacementGroupInfo)
    from .object_ref import ObjectRef, _reconstruct_ref
    from .task_spec import SchedulingStrategy, TaskSpec, TaskType

    sid = 1
    for cls in (_ids.JobId, _ids.NodeId, _ids.WorkerId, _ids.ActorId,
                _ids.PlacementGroupId, _ids.TaskId, _ids.ObjectId):
        register_struct(sid, cls,
                        encode=lambda o: (o.binary(),),
                        decode=lambda vals, _c=cls: _c(vals[0]))
        sid += 1
    # enums (plain Enum, not IntEnum — encode .value)
    register_struct(16, TaskType,
                    encode=lambda o: (o.value,),
                    decode=lambda v: TaskType(v[0]))
    register_struct(17, ActorState,
                    encode=lambda o: (o.value,),
                    decode=lambda v: ActorState(v[0]))
    # deserializing a ref IS a borrow — route through the same constructor
    # the pickle path (__reduce__) used so the borrower protocol counts it
    register_struct(18, ObjectRef,
                    encode=lambda o: (o.id, o.owner, o._call_site),
                    decode=lambda v: _reconstruct_ref(*v))
    register_struct(19, SchedulingStrategy)
    register_struct(20, TaskSpec)
    register_struct(21, ActorInfo)
    register_struct(22, NodeInfo)
    register_struct(23, JobInfo)
    register_struct(24, PlacementGroupInfo)


_register_defaults()
_try_native_decode()
