"""Per-task/actor runtime environments.

Equivalent of the reference's runtime_env machinery (ref:
dashboard/modules/runtime_env/runtime_env_agent.py:161 CreateRuntimeEnv;
python/ray/_private/runtime_env/working_dir.py + py_modules.py packaging;
runtime_env/packaging.py zip-and-upload protocol).

Design: the submitting process validates the env, zips any local
directories, and uploads them as content-addressed blobs in the GCS KV
("renv" namespace) — the same channel function exports already ride.
Workers are DEDICATED to one environment (reference semantics:
worker_pool.cc keys PopWorker by runtime_env hash): the node's lease
dispatch only hands a task to a worker bound to the same env hash, and a
fresh worker applies the env exactly once before its first task —
env_vars into os.environ, extracted working_dir as cwd + sys.path head,
py_modules onto sys.path.

pip envs install into per-requirement-set venvs on the worker host
(--system-site-packages so the base stack stays importable); pip's
standard source controls (PIP_INDEX_URL / --no-index / --find-links)
point at a mirror or wheelhouse on air-gapped pods. conda/container
remain gated: a clear error beats a silent ignore.
"""
from __future__ import annotations

import hashlib
import io
import json
import os
import sys
import tempfile
import zipfile
from typing import Callable, Dict, List, Optional

ALLOWED_KEYS = {"env_vars", "working_dir", "py_modules", "pip", "config",
                "container"}
# conda stays gated by design (README "runtime_env design stance"):
# TPU hosts run hermetic images whose Python stack must match the
# baked-in jax/libtpu; pip-in-venv (--system-site-packages) layers on
# top of it, while a conda env REPLACES the interpreter and would
# detach workers from the host's TPU stack. Container isolation is the
# supported heavyweight path.
GATED_KEYS = {"conda", "image_uri", "uv"}
# ref: runtime_env/packaging.py GCS_STORAGE_MAX_SIZE guard
MAX_PACKAGE_BYTES = 500 * 1024 * 1024
_EXCLUDE_DIRS = {"__pycache__", ".git", ".venv", "node_modules"}

KV_NAMESPACE = "renv"


def validate(renv: Optional[dict]) -> Optional[dict]:
    """Normalize and reject unknown/gated keys early, in the submitter."""
    if not renv:
        return None
    gated = GATED_KEYS & renv.keys()
    if gated:
        raise ValueError(
            f"runtime_env keys {sorted(gated)} are not supported on this "
            f"runtime: TPU hosts run hermetic images; ship code via "
            f"working_dir/py_modules/pip and configuration via env_vars")
    unknown = renv.keys() - ALLOWED_KEYS
    if unknown:
        raise ValueError(f"unknown runtime_env keys {sorted(unknown)}; "
                         f"supported: {sorted(ALLOWED_KEYS)}")
    out: dict = {}
    env_vars = renv.get("env_vars") or {}
    if env_vars:
        if not isinstance(env_vars, dict):
            raise TypeError("env_vars must be a dict")
        out["env_vars"] = {str(k): str(v) for k, v in env_vars.items()}
    if renv.get("working_dir"):
        out["working_dir"] = str(renv["working_dir"])
    mods = renv.get("py_modules") or []
    if mods:
        out["py_modules"] = [str(m) for m in mods]
    if "container" in renv and renv["container"] is not None:
        cont = renv["container"]
        # ref: runtime_env/container.py (podman wrapper there). Shape:
        # {"image": str, "run_options": [str]}; workers for this env are
        # LAUNCHED inside the container via the configured launcher
        # (config.container_launcher; scripts/container_worker_launcher
        # is the docker reference) — a running worker can't be moved
        # into one after the fact.
        if isinstance(cont, str):
            cont = {"image": cont}
        if not isinstance(cont, dict) or not cont.get("image"):
            raise TypeError('container must be {"image": str, '
                            '"run_options": [str]} or an image string')
        out["container"] = {
            "image": str(cont["image"]),
            "run_options": [str(o) for o in cont.get("run_options", [])],
        }
    if "pip" in renv and renv["pip"] is not None:
        pip = renv["pip"]
        # ref: runtime_env/pip.py — list of requirement strings, or
        # {"packages": [...], "pip_install_options": [...]}. Installs go
        # into a per-requirement-set venv on the worker host; standard
        # pip env (PIP_INDEX_URL / PIP_NO_INDEX / PIP_FIND_LINKS) and
        # the explicit options control where packages come from — on an
        # air-gapped pod that is a local mirror or wheelhouse.
        if isinstance(pip, (list, tuple)):
            if not pip:
                raise ValueError("runtime_env pip list must be non-empty")
            out["pip"] = {"packages": [str(p) for p in pip],
                          "pip_install_options": []}
        elif isinstance(pip, dict):
            pkgs = pip.get("packages")
            if not pkgs:
                raise ValueError("runtime_env pip dict needs 'packages'")
            out["pip"] = {
                "packages": [str(p) for p in pkgs],
                "pip_install_options": [
                    str(o) for o in pip.get("pip_install_options") or []]}
        else:
            raise TypeError("pip must be a list of requirements or a "
                            "{'packages': [...]} dict")
    if renv.get("config"):
        out["config"] = dict(renv["config"])
    return out or None


def _zip_dir(path: str) -> bytes:
    """Deterministic zip of a directory tree (sorted walk, zeroed
    timestamps) so identical trees hash identically across submitters."""
    buf = io.BytesIO()
    total = 0
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
        for root, dirs, files in os.walk(path):
            dirs[:] = sorted(d for d in dirs if d not in _EXCLUDE_DIRS)
            for name in sorted(files):
                full = os.path.join(root, name)
                rel = os.path.relpath(full, path)
                try:
                    data = open(full, "rb").read()
                except OSError:
                    continue  # sockets, vanished tmpfiles
                total += len(data)
                if total > MAX_PACKAGE_BYTES:
                    raise ValueError(
                        f"runtime_env package {path!r} exceeds "
                        f"{MAX_PACKAGE_BYTES >> 20} MiB")
                info = zipfile.ZipInfo(rel, date_time=(1980, 1, 1, 0, 0, 0))
                info.compress_type = zipfile.ZIP_DEFLATED
                zf.writestr(info, data)
    return buf.getvalue()


def _upload_dir(path: str, kv_put: Callable[[str, bytes], None]) -> dict:
    path = os.path.abspath(os.path.expanduser(path))
    if not os.path.isdir(path):
        raise FileNotFoundError(f"runtime_env directory {path!r} not found")
    blob = _zip_dir(path)
    sha = hashlib.sha1(blob).hexdigest()
    kv_put(f"pkg:{sha}", blob)
    return {"pkg": sha, "name": os.path.basename(path.rstrip(os.sep))}


def package(renv: Optional[dict],
            kv_put: Callable[[str, bytes], None]) -> Optional[dict]:
    """Submitter side: replace local paths with content-addressed KV
    references, then stamp the whole env with its hash (the worker-pool
    dedication key)."""
    renv = validate(renv)
    if renv is None:
        return None
    out = dict(renv)
    if "working_dir" in out:
        out["working_dir"] = _upload_dir(out["working_dir"], kv_put)
    if "py_modules" in out:
        out["py_modules"] = [_upload_dir(m, kv_put)
                             for m in out["py_modules"]]
    out["_hash"] = hashlib.sha1(
        json.dumps(out, sort_keys=True).encode()).hexdigest()[:16]
    return out


_FP_TTL = 5.0  # seconds a directory fingerprint stays cached
_fp_cache: Dict[str, tuple] = {}  # path -> (monotonic_ts, fingerprint)


def dir_fingerprint(path: str) -> str:
    """Cheap content fingerprint (relpath, size, mtime_ns of every file)
    so submitter-side caches notice edited working_dirs without paying a
    full re-zip per submission. The walk itself is memoized for a few
    seconds — task-submission hot loops must not pay one stat() per
    tracked file per .remote() call."""
    import time

    path = os.path.abspath(os.path.expanduser(path))
    hit = _fp_cache.get(path)
    now = time.monotonic()
    if hit is not None and now - hit[0] < _FP_TTL:
        return hit[1]
    fp = _dir_fingerprint_uncached(path)
    _fp_cache[path] = (now, fp)
    return fp


def _dir_fingerprint_uncached(path: str) -> str:
    h = hashlib.sha1()
    for root, dirs, files in os.walk(path):
        dirs[:] = sorted(d for d in dirs if d not in _EXCLUDE_DIRS)
        for name in sorted(files):
            full = os.path.join(root, name)
            try:
                st = os.stat(full)
            except OSError:
                continue
            h.update(f"{os.path.relpath(full, path)}|{st.st_size}|"
                     f"{st.st_mtime_ns}\n".encode())
    return h.hexdigest()[:16]


def cache_key(renv: dict) -> str:
    """Cache key for a VALIDATED (pre-packaging) env: the env dict plus
    fingerprints of every referenced local directory — a path alone would
    serve stale packages after the user edits the tree."""
    fps = {}
    wd = renv.get("working_dir")
    if wd:
        fps["working_dir"] = dir_fingerprint(wd)
    for i, m in enumerate(renv.get("py_modules") or []):
        fps[f"py_modules.{i}"] = dir_fingerprint(m)
    return json.dumps({"env": renv, "fp": fps}, sort_keys=True)


def container_command(launcher: str, container: dict,
                      base_cmd: list) -> list:
    """THE launcher invocation contract, shared by the local Node and
    remote NodeAgent worker starts:
        <launcher> <image> [run_options...] -- <worker cmd...>
    (scripts/container_worker_launcher.sh is the docker reference)."""
    return [str(launcher), container["image"],
            *container.get("run_options", []), "--", *base_cmd]


def env_hash(packaged: Optional[dict]) -> str:
    """'' = the plain environment (no runtime_env)."""
    return packaged.get("_hash", "") if packaged else ""


def merge(base: Optional[dict], override: Optional[dict]) -> Optional[dict]:
    """Job-level default + per-task override (ref:
    runtime_env.py:merge_runtime_env): env_vars union (task wins),
    other keys replaced wholesale."""
    if not base:
        return override
    if not override:
        return base
    out = dict(base)
    out.update({k: v for k, v in override.items() if k != "env_vars"})
    ev = dict(base.get("env_vars") or {})
    ev.update(override.get("env_vars") or {})
    if ev:
        out["env_vars"] = ev
    out.pop("_hash", None)
    return out


# -- worker side --------------------------------------------------------------

def _cache_root() -> str:
    return os.path.join(tempfile.gettempdir(), "ray_tpu_runtime_env")


def _extract(ref: dict, kv_get: Callable[[str], bytes]) -> str:
    """Fetch+extract a packaged dir into the shared content-addressed
    cache. Concurrent workers race benignly: extraction goes to a
    process-private temp dir, then one atomic rename wins."""
    sha = ref["pkg"]
    dest = os.path.join(_cache_root(), sha)
    if os.path.isdir(dest):
        return dest
    blob = kv_get(f"pkg:{sha}")
    if blob is None:
        raise RuntimeError(f"runtime_env package {sha} missing from KV")
    os.makedirs(_cache_root(), exist_ok=True)
    tmp = tempfile.mkdtemp(dir=_cache_root(), prefix=f".{sha}.")
    with zipfile.ZipFile(io.BytesIO(blob)) as zf:
        zf.extractall(tmp)
    try:
        os.rename(tmp, dest)
    except OSError:
        if not os.path.isdir(dest):  # lost the race is fine; else real error
            raise
        import shutil

        shutil.rmtree(tmp, ignore_errors=True)
    return dest


def _venv_site_packages(venv_dir: str) -> str:
    vpy = f"python{sys.version_info.major}.{sys.version_info.minor}"
    return os.path.join(venv_dir, "lib", vpy, "site-packages")


def _ensure_pip_env(pip_spec: dict) -> str:
    """Create (or reuse) the venv for this requirement set; returns its
    site-packages path. Cache key = packages + options + interpreter
    version; builds are atomic-rename like _extract so concurrent
    workers race benignly (ref: runtime_env/pip.py PipProcessor)."""
    import shutil
    import subprocess

    key = hashlib.sha1(json.dumps(
        {"pkgs": sorted(pip_spec["packages"]),
         "opts": pip_spec.get("pip_install_options") or [],
         "py": sys.version_info[:2]},
        sort_keys=True).encode()).hexdigest()[:16]
    dest = os.path.join(_cache_root(), f"venv_{key}")
    if os.path.isdir(dest):
        return _venv_site_packages(dest)
    os.makedirs(_cache_root(), exist_ok=True)
    tmp = tempfile.mkdtemp(dir=_cache_root(), prefix=f".venv_{key}.")
    try:
        # --system-site-packages: the worker's own stack (ray_tpu, jax,
        # numpy) must stay importable alongside the extra packages
        subprocess.run([sys.executable, "-m", "venv",
                        "--system-site-packages", tmp],
                       check=True, capture_output=True, timeout=120)
        vpip = os.path.join(tmp, "bin", "python")
        out = subprocess.run(
            [vpip, "-m", "pip", "install", "--no-input",
             *pip_spec.get("pip_install_options", []),
             *pip_spec["packages"]],
            capture_output=True, text=True, timeout=600)
        if out.returncode != 0:
            raise RuntimeError(
                f"runtime_env pip install failed "
                f"(packages={pip_spec['packages']}):\n{out.stderr[-2000:]}")
        os.rename(tmp, dest)
    except KeyboardInterrupt:
        shutil.rmtree(tmp, ignore_errors=True)
        raise  # never swallow interrupts, winner or not
    except Exception:
        shutil.rmtree(tmp, ignore_errors=True)
        if not os.path.isdir(dest):  # a concurrent builder may have won
            raise
    return _venv_site_packages(dest)


def apply(packaged: Optional[dict],
          kv_get: Callable[[str], bytes]) -> None:
    """Apply an environment to THIS process (called once, before the
    worker's first task — the worker is dedicated from then on)."""
    if not packaged:
        return
    for k, v in (packaged.get("env_vars") or {}).items():
        os.environ[k] = v
    pip_spec = packaged.get("pip")
    if pip_spec:
        site = _ensure_pip_env(pip_spec)
        if site not in sys.path:
            sys.path.insert(0, site)
    paths: List[str] = []
    wd = packaged.get("working_dir")
    if wd:
        dest = _extract(wd, kv_get)
        paths.append(dest)
        os.chdir(dest)
    for ref in packaged.get("py_modules") or []:
        dest = _extract(ref, kv_get)
        # a py_modules entry IS the importable package: expose it under
        # its original name via an aliasing dir on sys.path (the zip is
        # rooted inside the package; ref: py_modules.py upload contract)
        alias_root = dest + "_pkg"
        os.makedirs(alias_root, exist_ok=True)
        link = os.path.join(alias_root, ref["name"])
        if not os.path.lexists(link):
            try:
                os.symlink(dest, link)
            except FileExistsError:
                pass  # concurrent worker won the race
        paths.append(alias_root)
    for p in reversed(paths):
        if p not in sys.path:
            sys.path.insert(0, p)
