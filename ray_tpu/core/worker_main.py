"""Worker process entrypoint.

Equivalent of the reference's worker side of CoreWorker
(ref: src/ray/core_worker/core_worker.cc:2523 ExecuteTask;
python/ray/_raylet.pyx:1253 execute_task;
transport/actor_scheduling_queue.cc for ordered actor execution;
concurrency_group_manager.cc for threaded/async actors).

A worker connects back to its node over a Unix socket RpcChannel, registers,
then serves pushed tasks. Normal tasks run one-at-a-time on the main executor
thread; actor tasks run on the actor's scheduling queue (FIFO by client
sequence number, with max_concurrency threads, or an asyncio loop for async
actors). Blocking runtime calls (get/put/submit) are proxied back over the
channel to the node — the worker never blocks its RPC reader.
"""
from __future__ import annotations

import argparse
import asyncio
import inspect
import os
import queue
import sys
import threading
import time
import traceback
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, Optional

import cloudpickle

from . import serialization
from .ids import ActorId, WorkerId
from .object_ref import ObjectRef
from .object_store import SegmentReader
from .rpc import RpcChannel, connect
from .task_spec import (ARG_REF, ARG_VALUE, STREAMING_RETURNS, TaskSpec,
                        TaskType)


# line-buffered stdout/stderr capture now lives in util/logs.py
# (StreamTee -> LogBatcher): lines are stamped with {stream, seq, ts,
# job/task/actor} from the current-task contextvar, batched, and
# rate-limited before riding the channel — see that module's docstring.
from ..util.logs import LogBatcher, StreamTee as _StreamTee  # noqa: E402


def _aiter_to_iter(agen):
    """Drain an async generator synchronously (streaming async-actor
    methods; the channel call between items blocks anyway)."""
    loop = asyncio.new_event_loop()
    try:
        while True:
            try:
                yield loop.run_until_complete(agen.__anext__())
            except StopAsyncIteration:
                break
    finally:
        loop.close()


class _ActorLane:
    """Per-caller sequencing lane (ref: the reference's client-side actor
    task sequencing — each submitter numbers its own calls). The head's
    routed lane is key b""; direct callers get their own lane keyed by
    caller worker id. A direct lane carries a GATE: the number of
    head-lane tasks that must have dispatched before the lane may run,
    which pins the caller's routed->direct transition to per-caller FIFO
    (its earlier routed calls all carry head seqs below the gate).

    ``era`` is the caller's connection-era token: bumped by the caller
    each time it (re)establishes the peer connection, at which point the
    caller also restarts its seq numbering at 0. A higher era resets the
    lane (frames lost in the dead connection would otherwise leave
    ``expected`` behind forever); a lower era marks a straggler frame
    from a connection whose unanswered calls the caller has already
    recovered through the routed path — dropped, never a lost result."""

    __slots__ = ("expected", "buffer", "gate", "era")

    def __init__(self, gate: int = 0, era: int = 0):
        self.expected = 0
        self.buffer: Dict[int, TaskSpec] = {}
        self.gate = gate
        self.era = era


class ActorQueue:
    """Ordered execution queue for one actor instance.
    (ref: transport/actor_scheduling_queue.cc — enforce seq order;
    out_of_order_actor_submit_queue.cc for max_concurrency > 1).

    Tasks arrive on per-caller lanes (see _ActorLane); within a lane,
    execution is dispatched in seq order. Lanes are independent — two
    callers' calls interleave arbitrarily, exactly as they did racing
    through the head."""

    def __init__(self, worker: "WorkerProcess", instance: Any, spec: TaskSpec):
        self.worker = worker
        self.instance = instance
        self.max_concurrency = max(1, spec.max_concurrency)
        self.is_async = spec.is_async_actor
        self._lanes: Dict[bytes, _ActorLane] = {}
        self._head_dispatched = 0
        self._lock = threading.Lock()
        self._pool = ThreadPoolExecutor(max_workers=self.max_concurrency,
                                        thread_name_prefix="actor")
        # named concurrency groups: each an independent execution lane with
        # its own parallelism cap; calls within a group keep submission
        # order relative to each other (FIFO into a bounded pool) while
        # groups never block one another (ref:
        # transport/concurrency_group_manager.cc)
        self._group_pools: Dict[str, ThreadPoolExecutor] = {}
        for gname, size in (spec.concurrency_groups or {}).items():
            self._group_pools[gname] = ThreadPoolExecutor(
                max_workers=max(1, int(size)),
                thread_name_prefix=f"actor-{gname}")
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        if self.is_async:
            self._loop = asyncio.new_event_loop()
            threading.Thread(target=self._loop.run_forever, daemon=True,
                             name="actor-asyncio").start()

    def _pool_for(self, spec: TaskSpec) -> ThreadPoolExecutor:
        return self._group_pools.get(spec.concurrency_group, self._pool)

    def push(self, spec: TaskSpec, gate: int = 0, era: int = 0) -> None:
        # Dispatch under the lock: push_task messages are handled by a pool
        # of RPC threads, so releasing the lock before pool.submit would let
        # two threads invert the sequence order.
        lane_key = spec.owner_id.binary() if spec.owner_id is not None else b""
        with self._lock:
            lane = self._lanes.get(lane_key)
            if lane is None:
                lane = self._lanes[lane_key] = _ActorLane(gate, era)
            elif era > lane.era:
                # new connection era: the caller restarted seq numbering
                # at 0 and has recovered everything unanswered from the
                # old connection through the routed path — buffered old-
                # era frames are covered by that recovery, and keeping
                # the old `expected` would strand the lane forever if any
                # old-era frame died in the dropped socket
                lane.era = era
                lane.expected = 0
                lane.buffer.clear()
                lane.gate = gate
            elif era < lane.era:
                return  # straggler from a recovered (dead) era
            elif gate > lane.gate:
                lane.gate = gate
            lane.buffer[spec.seq_no] = spec
            self._drain_locked()

    def _drain_locked(self) -> None:
        """Dispatch every runnable task: head lane first (its progress
        opens direct-lane gates), then gated direct lanes; loop until a
        full pass makes no progress."""
        progress = True
        while progress:
            progress = False
            head = self._lanes.get(b"")
            if head is not None:
                while head.expected in head.buffer:
                    s = head.buffer.pop(head.expected)
                    head.expected += 1
                    self._head_dispatched += 1
                    self._dispatch(s)
                    progress = True
            for key, lane in self._lanes.items():
                if key == b"" or self._head_dispatched < lane.gate:
                    continue
                while lane.expected in lane.buffer:
                    s = lane.buffer.pop(lane.expected)
                    lane.expected += 1
                    self._dispatch(s)
                    progress = True

    def _dispatch(self, s: TaskSpec) -> None:
        if s.concurrency_group \
                and s.concurrency_group not in self._group_pools:
            self._pool.submit(
                self.worker._report_error, s,
                ValueError(
                    f"concurrency group {s.concurrency_group!r} was "
                    f"not declared in concurrency_groups="
                    f"{sorted(self._group_pools)}"))
            return
        if self.is_async:
            asyncio.run_coroutine_threadsafe(self._run_async(s), self._loop)
        else:
            self._pool_for(s).submit(self.worker.execute_task, s,
                                     self.instance)

    async def _run_async(self, spec: TaskSpec) -> None:
        if self._is_coroutine(spec):
            await self.worker.execute_task_async(spec, self.instance)
        else:
            loop = asyncio.get_event_loop()
            await loop.run_in_executor(None, self.worker.execute_task, spec,
                                       self.instance)

    def _is_coroutine(self, spec: TaskSpec) -> bool:
        try:
            method = getattr(self.instance, spec.method_name)
            return inspect.iscoroutinefunction(method)
        except Exception:
            return False


class WorkerProcess:
    def __init__(self, channel: RpcChannel, worker_id: WorkerId, node_id_hex: str):
        self.channel = channel
        self.worker_id = worker_id
        self.node_id_hex = node_id_hex
        self.reader = SegmentReader()
        self._fn_cache: Dict[str, Any] = {}
        self._task_queue: "queue.Queue[Optional[TaskSpec]]" = queue.Queue()
        self._actor: Optional[ActorQueue] = None
        self._actor_id: Optional[ActorId] = None
        self._cancelled: set = set()
        self._renv_applied = False  # runtime_env applied once, first task
        self._stop = threading.Event()
        # register the worker-mode runtime so `ray_tpu.get/put/remote` work in tasks
        from . import runtime as runtime_mod

        self.runtime = runtime_mod.WorkerRuntime(self)
        runtime_mod.set_runtime(self.runtime)
        # every ObjectRef deserialized in this process is a borrow the head
        # must count (ref: reference_count.h:61 borrower protocol)
        from .object_ref import _set_borrow_hook

        _set_borrow_hook(self.runtime.register_borrowed_ref)
        # metrics export: this process's registry (user metrics observed
        # inside tasks + the built-in rpc/store/get instruments) ships
        # deltas to the head — throttled after each task, plus a periodic
        # sweep so idle-period observations still surface (the
        # metrics-agent analog; ref: python/ray/_private/metrics_agent.py)
        self._metrics_last_flush = 0.0
        self._metrics_flush_lock = threading.Lock()
        self._metrics_backlog: list = []  # deltas that failed to ship
        from .config import DEFAULT as _cfg

        self._metrics_interval = max(
            0.1, float(_cfg.metrics_export_interval_s))
        # direct-dispatch state must exist before the metrics loop starts
        # (it flushes the batched direct-event stream on the same thread)
        self._direct_reply = {}
        self._direct_lock = threading.Lock()
        self._devents: list = []
        self._devents_interval = max(0.05, float(_cfg.direct_event_flush_s))
        self._devents_batch = max(1, int(_cfg.direct_event_batch))
        threading.Thread(target=self._metrics_loop, daemon=True,
                         name="worker-metrics").start()
        # outbound log plane: stdout/stderr tees and the structured
        # logger emit into this batcher; attribution is read from the
        # current-task contextvar at write time (async-actor lines on
        # one loop thread attribute to their own asyncio.Task context)
        self.log_batcher = LogBatcher(
            send=lambda p: self.channel.notify("worker_log", p),
            task_ids=self._current_task_ids,
            batch_lines=int(_cfg.log_batch_lines),
            flush_interval_s=float(_cfg.log_flush_interval_s),
            rate_lines_per_s=float(_cfg.log_rate_limit_lines_per_s))
        self._profiling = threading.Lock()  # one profile run at a time
        # compiled-graph executor (ray_tpu/cgraph): created lazily on the
        # first cgraph_load so plain task workers never pay the import
        self._cgraph = None
        # direct dispatch (docs/DISPATCH.md): tasks submitted straight to
        # this worker by a peer (another worker, or the driver over this
        # node channel) reply on the channel they arrived on, not via the
        # head's task_done intake; the reply map / batched-event state is
        # initialized above, before the metrics thread starts
        self._direct_server = None
        self.direct_addr: Optional[str] = None

    def start_direct_server(self, sock_dir: str) -> None:
        """Listen for peer direct-call connections (worker-to-worker and
        driver-to-remote-worker submissions). Unix socket next to the
        node's: same-host peers connect directly; cross-host callers fall
        back to head routing when the connect fails."""
        from .rpc import RpcServer

        path = os.path.join(sock_dir, f"dw_{self.worker_id.hex()[:12]}.sock")

        def factory(channel: RpcChannel):
            return lambda method, payload: self.handle_direct(
                channel, method, payload)

        try:
            self._direct_server = RpcServer(path, factory, family="AF_UNIX",
                                            num_handler_threads=4)
            self.direct_addr = path
        except Exception:
            self.direct_addr = None

    def handle_direct(self, channel: RpcChannel, method: str, payload):
        """Handler for peer direct-call channels (and the direct_submit /
        direct_result frames that ride the node channel when the driver is
        the caller)."""
        if method == "direct_submit":
            spec: TaskSpec = payload["spec"]
            if self._actor is None or self._actor_id != spec.actor_id:
                # stale placement (this process hosts no/another actor —
                # e.g. an OS-recycled address): tell the caller to
                # invalidate its cache and re-resolve via the head
                channel.notify("direct_result",
                               {"task_id": spec.task_id,
                                "actor_id": spec.actor_id, "stale": True})
                return None
            with self._direct_lock:
                self._direct_reply[spec.task_id] = channel
            self._actor.push(spec, gate=int(payload.get("gate", 0)),
                             era=int(payload.get("lane", 0)))
            return None
        if method == "direct_result":
            # this worker is the CALLER: a peer finished our direct task
            self.runtime.on_direct_result(payload)
            return None
        if method == "ping":
            return "pong"
        raise ValueError(f"unknown direct message {method}")

    def _direct_event(self, spec: TaskSpec, t_start: float, t_end: float,
                      error: bool) -> None:
        """Record one direct task's lifecycle for the batched event
        stream; flushes by size here and by time in the metrics loop."""
        tid = spec.task_id.hex()
        aid = spec.actor_id.hex() if spec.actor_id else ""
        flush = None
        with self._direct_lock:
            self._devents.append(
                {"task_id": tid, "name": spec.description,
                 "state": "RUNNING", "time": t_start, "actor_id": aid})
            self._devents.append(
                {"task_id": tid, "name": spec.description,
                 "state": "FAILED" if error else "FINISHED",
                 "time": t_end, "actor_id": aid})
            if len(self._devents) >= self._devents_batch:
                flush, self._devents = self._devents, []
        if flush:
            self._send_devents(flush)

    def _flush_devents(self) -> None:
        with self._direct_lock:
            flush, self._devents = self._devents, []
        if flush:
            self._send_devents(flush)

    def _send_devents(self, events: list) -> None:
        try:
            self.channel.notify("task_events_batch", events)
        except Exception:
            pass

    def _current_task_ids(self):
        spec = self.runtime.current_task()
        if spec is None:
            # actor workers between calls: background threads still
            # attribute to the resident actor
            aid = self._actor_id.hex() if self._actor_id else ""
            return ("", "", aid)
        aid = spec.actor_id.hex() if spec.actor_id else ""
        return (spec.job_id.hex(), spec.task_id.hex(), aid)

    def _flush_metrics(self, min_interval: Optional[float] = None) -> None:
        now = time.monotonic()
        with self._metrics_flush_lock:
            if min_interval is not None \
                    and now - self._metrics_last_flush < min_interval:
                return
            self._metrics_last_flush = now
            from ..util import metrics as metrics_mod

            try:
                deltas = metrics_mod.carry_backlog(self._metrics_backlog)
            except Exception:
                return
            if not deltas:
                return
            if self.channel.closed:
                self._metrics_backlog = deltas
                return
            self._metrics_backlog = []
            # notify inside the lock (it only enqueues to the writer
            # thread): a later gauge snapshot shipping before an earlier
            # one would roll the head's last-write-wins value backwards
            try:
                self.channel.notify("metrics_push", {"deltas": deltas})
            except Exception:
                self._metrics_backlog = deltas

    def _metrics_loop(self) -> None:
        last_dev = 0.0
        while not self._stop.is_set() and not self.channel.closed:
            self._stop.wait(min(self._metrics_interval,
                                self._devents_interval))
            now = time.monotonic()
            if now - last_dev >= self._devents_interval:
                last_dev = now
                self._flush_devents()
            self._flush_metrics(min_interval=self._metrics_interval)

    # -- incoming RPC ----------------------------------------------------------

    def handle(self, method: str, payload: Any) -> Any:
        if method == "push_task":
            spec: TaskSpec = payload
            if spec.task_type == TaskType.ACTOR_TASK and self._actor is not None:
                self._actor.push(spec)
            else:
                self._task_queue.put(spec)
            return None
        if method in ("direct_submit", "direct_result"):
            # the driver submits direct calls over this node channel (it
            # already connects straight to this process); replies ride it
            # back as direct_result frames
            return self.handle_direct(self.channel, method, payload)
        if method == "ping":
            return "pong"
        if method == "dump_stacks":
            # answered from the RPC handler pool — works while the main
            # executor thread is wedged in user code or a blocking get()
            # (ref: `ray stack`; the SIGUSR1 faulthandler hook remains
            # the signal-safe fallback when even RPC is unresponsive)
            from ..util.introspect import dump_stacks

            return dump_stacks()
        if method == "profile":
            from ..util.introspect import SamplingProfiler

            if not self._profiling.acquire(blocking=False):
                raise RuntimeError("a profile run is already active "
                                   "on this worker")
            try:
                prof = SamplingProfiler(
                    interval_s=float((payload or {}).get("interval_s",
                                                         0.01)))
                res = prof.run(float((payload or {}).get("duration_s",
                                                         5.0)))
            finally:
                self._profiling.release()
            res["pid"] = os.getpid()
            return res
        if method == "cancel_task":
            self._cancelled.add(payload)
            return None
        if method == "cgraph_load":
            # resident-loop execution mode: build channel endpoints + the
            # method dispatch table once, then run the static plan beside
            # normal task dispatch (ray_tpu/cgraph/executor.py)
            if self._cgraph is None:
                from ..cgraph.executor import CGraphExecutor

                self._cgraph = CGraphExecutor(self)
            return self._cgraph.load(payload)
        if method == "cgraph_push":
            if self._cgraph is not None:
                self._cgraph.push(payload)
            return None
        if method == "cgraph_stop":
            if self._cgraph is not None:
                return self._cgraph.stop(payload["graph_id"])
            return True
        if method == "flightrec_snapshot":
            from ..perf.recorder import get_recorder
            return get_recorder().snapshot(
                clear=bool((payload or {}).get("clear")))
        if method == "flightrec_set_enabled":
            from ..perf.recorder import set_enabled
            set_enabled(bool((payload or {}).get("on", True)))
            return True
        if method == "kill_actor":
            os._exit(0)
        if method == "shutdown":
            self._stop.set()
            if self._cgraph is not None:
                self._cgraph.stop_all()
            self._task_queue.put(None)
            return None
        raise ValueError(f"unknown method {method}")

    # -- task execution --------------------------------------------------------

    def run(self) -> None:
        while not self._stop.is_set() and not self.channel.closed:
            try:
                spec = self._task_queue.get(timeout=0.25)
            except queue.Empty:
                continue
            if spec is None:
                break
            self.execute_task(spec, self._actor.instance if self._actor else None)

    def _get_function(self, func_id: str):
        fn = self._fn_cache.get(func_id)
        if fn is None:
            blob = self.channel.call("get_function", func_id, timeout=60)
            fn = cloudpickle.loads(blob)
            self._fn_cache[func_id] = fn
        return fn

    def resolve_args(self, spec: TaskSpec):
        ref_ids = [a[1].id for a in spec.args if a[0] == ARG_REF]
        ref_ids += [a[1].id for a in spec.kwargs.values() if a[0] == ARG_REF]
        values = {}
        if ref_ids:
            fetched = self.runtime.get_many(ref_ids)
            values = dict(zip([r.hex() for r in ref_ids], fetched))
        args = [
            values[a[1].id.hex()] if a[0] == ARG_REF else serialization.loads(a[1])
            for a in spec.args
        ]
        kwargs = {
            k: (values[a[1].id.hex()] if a[0] == ARG_REF else serialization.loads(a[1]))
            for k, a in spec.kwargs.items()
        }
        return args, kwargs

    def execute_task(self, spec: TaskSpec, instance: Any = None) -> None:
        if spec.task_id in self._cancelled:
            self._report_error(spec, _make_cancelled_error(spec))
            return
        if spec.task_id in self._direct_reply:
            spec.__dict__["_t_exec0"] = time.time()  # direct event stream
        if spec.runtime_env and not self._renv_applied:
            # the node's lease dispatch guarantees this worker is either
            # fresh or already dedicated to exactly this env, so a single
            # application covers the worker's whole life
            from . import runtime_env as renv_mod

            try:
                renv_mod.apply(
                    spec.runtime_env,
                    lambda key: self.channel.call(
                        "kv_get",
                        {"key": key, "namespace": renv_mod.KV_NAMESPACE},
                        timeout=120))
            except BaseException as e:
                from ..exceptions import RuntimeEnvSetupError

                self._report_error(spec, RuntimeEnvSetupError(
                    f"runtime_env setup failed: {e!r}"))
                return
            self._renv_applied = True
        token = self.runtime.set_current_task(spec)
        # tracing: the submitter's span context re-activates around the
        # execution and resets afterwards (tracing.task_span handles the
        # token; a leak would misattribute later tasks on this thread)
        from ..util.tracing import task_span

        with task_span(spec):
            self._execute_task_inner(spec, instance, token)
        # ship metric deltas promptly after each task (throttled) so a
        # head scrape right after ray_tpu.get() sees them
        self._flush_metrics(min_interval=0.25)

    def _execute_task_inner(self, spec: TaskSpec, instance: Any,
                            token) -> None:
        try:
            args, kwargs = self.resolve_args(spec)
            if spec.task_type == TaskType.NORMAL_TASK:
                fn = self._get_function(spec.func_id)
                result = fn(*args, **kwargs)
            elif spec.task_type == TaskType.ACTOR_CREATION_TASK:
                cls = self._get_function(spec.func_id)
                inst = cls(*args, **kwargs)
                self._actor = ActorQueue(self, inst, spec)
                self._actor_id = spec.actor_id
                result = None
            else:  # ACTOR_TASK
                method = getattr(instance, spec.method_name)
                if inspect.iscoroutinefunction(method):
                    result = asyncio.run(method(*args, **kwargs))
                else:
                    result = method(*args, **kwargs)
            self._report_success(spec, result)
        except BaseException as e:  # noqa: BLE001 — remote errors must be shipped back
            self._report_error(spec, e)
        finally:
            self.runtime.clear_current_task(token)

    async def execute_task_async(self, spec: TaskSpec, instance: Any) -> None:
        from ..util.tracing import task_span

        token = self.runtime.set_current_task(spec)
        with task_span(spec):
            try:
                args, kwargs = self.resolve_args(spec)
                method = getattr(instance, spec.method_name)
                result = await method(*args, **kwargs)
                self._report_success(spec, result)
            except BaseException as e:  # noqa: BLE001
                self._report_error(spec, e)
            finally:
                self.runtime.clear_current_task(token)
        self._flush_metrics(min_interval=0.25)

    # -- result reporting ------------------------------------------------------

    def _pop_direct_reply(self, task_id) -> Optional[RpcChannel]:
        with self._direct_lock:
            return self._direct_reply.pop(task_id, None)

    def _report_direct_success(self, spec: TaskSpec, result: Any,
                               reply: RpcChannel) -> None:
        """Ship a direct task's results straight back to the caller.

        Small ref-free results travel inline on the peer channel — zero
        head traffic. Results that are large OR contain ObjectRefs go
        through the head's store instead (("stored") markers): nested
        refs need the head's borrower pins (_nested_refs) so the
        producer's own reference dropping at function exit can't free
        them before the caller deserializes."""
        from .config import DEFAULT as cfg

        if spec.num_returns == 0:
            outs = []
        elif spec.num_returns == 1:
            outs = [result]
        else:
            outs = list(result)
            if len(outs) != spec.num_returns:
                self._report_direct_error(spec, ValueError(
                    f"Task returned {len(outs)} values, expected "
                    f"{spec.num_returns}"), reply)
                return
        results = []
        for oid, value in zip(spec.return_ids(), outs):
            sobj = serialization.serialize(value)
            if sobj.contained_refs:
                for r in sobj.contained_refs:
                    self.runtime.ensure_published(r.id)
                data = sobj.to_bytes()
                self.channel.call("direct_result_stored", {
                    "object_id": oid, "data": data,
                    "borrowed": [r.id for r in sobj.contained_refs]})
                results.append(("stored", None))
            elif sobj.total_bytes <= cfg.max_direct_call_object_size:
                results.append(("inline", sobj.to_bytes()))
            else:
                name = self.channel.call(
                    "create_object",
                    {"object_id": oid, "size": sobj.total_bytes})
                mv = self.reader.read(name, sobj.total_bytes)
                sobj.write_into(mv)
                del mv
                self.reader.release(name)
                self.channel.call("seal_object", {"object_id": oid})
                results.append(("stored", None))
        t_end = time.time()
        self._direct_event(spec, spec.__dict__.get("_t_exec0", t_end),
                           t_end, error=False)
        reply.notify("direct_result", {
            "task_id": spec.task_id, "actor_id": spec.actor_id,
            "results": results, "error": None})

    def _report_direct_error(self, spec: TaskSpec, exc: BaseException,
                             reply: RpcChannel) -> None:
        from ..exceptions import TaskError

        if isinstance(exc, TaskError):
            err = exc
        else:
            err = TaskError(cause=exc,
                            remote_traceback=traceback.format_exc(),
                            task_desc=spec.description)
        try:
            blob = serialization.dumps(err)
        except Exception:
            blob = serialization.dumps(
                TaskError(remote_traceback=traceback.format_exc(),
                          task_desc=spec.description))
        t_end = time.time()
        self._direct_event(spec, spec.__dict__.get("_t_exec0", t_end),
                           t_end, error=True)
        reply.notify("direct_result", {
            "task_id": spec.task_id, "actor_id": spec.actor_id,
            "results": None, "error": blob})

    def _report_success(self, spec: TaskSpec, result: Any) -> None:
        from .config import DEFAULT as cfg

        if spec.num_returns == STREAMING_RETURNS:
            self._stream_generator(spec, result)
            return
        reply = self._pop_direct_reply(spec.task_id)
        if reply is not None:
            try:
                self._report_direct_success(spec, result, reply)
            except Exception as e:  # e.g. head channel died mid-store
                # the reply entry is already popped — report on the direct
                # channel we hold, NOT _report_error (whose routed
                # task_done the head would drop: direct tasks are never
                # in worker.in_flight, so the caller would hang)
                try:
                    self._report_direct_error(spec, e, reply)
                except Exception:
                    pass  # reply channel dead too: the caller's
                    # on_close recovery resubmits through the head
            return
        if spec.num_returns == 0:
            outs = []
        elif spec.num_returns == 1:
            outs = [result]
        else:
            outs = list(result)
            if len(outs) != spec.num_returns:
                self._report_error(
                    spec,
                    ValueError(
                        f"Task returned {len(outs)} values, expected {spec.num_returns}"),
                )
                return
        results = []
        borrowed = []  # aligned with results: [[oids], ...] per return
        return_ids = spec.return_ids()
        for oid, value in zip(return_ids, outs):
            sobj = serialization.serialize(value)
            for r in sobj.contained_refs:
                # direct-result refs nested in a routed return escape this
                # process: the head must own them before it pins them
                self.runtime.ensure_published(r.id)
            # refs nested inside EACH return value: the head pins them
            # until THAT return object dies, or this worker's own ref
            # dropping (function exit) can free them before the caller
            # deserializes — the borrower-protocol gap a GC cycle used
            # to mask (see on_task_done's nested-ref pin)
            borrowed.append([r.id for r in sobj.contained_refs])
            if sobj.total_bytes <= cfg.max_direct_call_object_size:
                results.append(("inline", sobj.to_bytes()))
            else:
                name = self.channel.call("create_object",
                                         {"object_id": oid, "size": sobj.total_bytes})
                mv = self.reader.read(name, sobj.total_bytes)
                sobj.write_into(mv)
                del mv  # drop the exported view before unmapping
                self.reader.release(name)
                self.channel.call("seal_object", {"object_id": oid})
                results.append(("stored", None))
        msg = {"task_id": spec.task_id, "results": results, "error": None}
        if any(borrowed):
            msg["borrowed"] = borrowed
        self.channel.notify("task_done", msg)

    def _stream_generator(self, spec: TaskSpec, result: Any) -> None:
        """Iterate the task's generator, reporting each item as it is
        produced (ref: _raylet.pyx execute_streaming_generator:868;
        ReportGeneratorItemReturns). The per-item call doubles as
        backpressure: the worker can't run ahead of the head's intake."""
        from .config import DEFAULT as cfg
        from .ids import ObjectId

        if hasattr(result, "__aiter__") and not hasattr(result, "__iter__"):
            result = _aiter_to_iter(result)  # async-generator methods
        n = 0
        try:
            for item in result:
                oid = ObjectId.for_task_return(spec.task_id, n)
                sobj = serialization.serialize(item)
                for r in sobj.contained_refs:
                    self.runtime.ensure_published(r.id)
                if sobj.total_bytes <= cfg.max_direct_call_object_size:
                    ok = self.channel.call("generator_item", {
                        "task_id": spec.task_id, "index": n,
                        "object_id": oid, "data": sobj.to_bytes()})
                    if ok is False:
                        break  # consumer dropped the generator
                else:
                    name = self.channel.call(
                        "create_object", {"object_id": oid,
                                          "size": sobj.total_bytes})
                    mv = self.reader.read(name, sobj.total_bytes)
                    sobj.write_into(mv)
                    del mv
                    self.reader.release(name)
                    self.channel.call("seal_object", {"object_id": oid})
                    ok = self.channel.call("generator_item", {
                        "task_id": spec.task_id, "index": n,
                        "object_id": oid})
                    if ok is False:
                        break  # consumer dropped the generator
                n += 1
        except BaseException as e:  # noqa: BLE001 — mid-stream failure
            self._report_error(spec, e)
            return
        finally:
            close = getattr(result, "close", None)
            if callable(close):
                try:
                    close()  # run the generator's finally blocks
                except Exception:
                    pass
        self.channel.notify("task_done", {
            "task_id": spec.task_id,
            "results": [],
            "streaming_count": n,
            "error": None,
        })

    def _report_error(self, spec: TaskSpec, exc: BaseException) -> None:
        from ..exceptions import TaskError

        reply = self._pop_direct_reply(spec.task_id)
        if reply is not None:
            self._report_direct_error(spec, exc, reply)
            return
        if isinstance(exc, TaskError):
            err = exc
        else:
            err = TaskError(cause=exc, remote_traceback=traceback.format_exc(),
                            task_desc=spec.description)
        try:
            blob = serialization.dumps(err)
        except Exception:
            blob = serialization.dumps(
                TaskError(remote_traceback=traceback.format_exc(),
                          task_desc=spec.description))
        self.channel.notify("task_done", {
            "task_id": spec.task_id,
            "results": None,
            "error": blob,
        })


def _make_cancelled_error(spec: TaskSpec):
    from ..exceptions import TaskCancelledError

    return TaskCancelledError(f"Task {spec.description} was cancelled")


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--address", required=True)
    parser.add_argument("--worker-id", required=True)
    parser.add_argument("--node-id", required=True)
    args = parser.parse_args()

    # SIGUSR1 dumps all thread stacks to stderr — the debugging hook for
    # "worker looks wedged" (ref: the reference's ray stack CLI).
    import faulthandler
    import signal

    faulthandler.register(signal.SIGUSR1, all_threads=True)

    # deterministic fault injection (env inherited from the node): frame
    # chaos applies to this worker's node channel and direct peer sockets
    from .. import chaos as _chaos_mod

    _chaos_mod.maybe_enable_from_env()

    worker_id = WorkerId.from_hex(args.worker_id)
    try:
        # auth token arrives via RTPU_AUTHKEY in the environment (connect's
        # default cluster_token() reads it), never on the command line
        channel = connect(args.address, name=f"worker-{args.worker_id[:8]}")
    except OSError:
        return  # node shut down while we were starting; exit quietly
    wp = WorkerProcess(channel, worker_id, args.node_id)
    channel.set_handler(wp.handle)
    from .config import DEFAULT as _cfg

    if int(_cfg.direct_worker_server):
        # peer-facing direct-call socket, advertised through register so
        # the head's resolve_actor can hand it to callers
        wp.start_direct_server(os.path.dirname(args.address))
    if os.environ.get("RTPU_WORKER_PROFILE"):
        # perf debugging: dump this worker's cProfile stats on exit
        import atexit
        import cProfile
        import pstats

        prof = cProfile.Profile()
        prof.enable()

        def _dump(pid=os.getpid()):
            prof.disable()
            pstats.Stats(prof).dump_stats(
                os.environ["RTPU_WORKER_PROFILE"] + f".{pid}")
        atexit.register(_dump)
        channel.on_close(lambda: (_dump(), os._exit(0)))
    else:
        channel.on_close(lambda: os._exit(0))
    resp = channel.call("register", {"worker_id": worker_id,
                                     "pid": os.getpid(),
                                     "direct_addr": wp.direct_addr},
                        timeout=30)
    if isinstance(resp, dict) and resp.get("forward_logs"):
        # tee prints into the attributed log plane (and still to the
        # local console); remote nodes additionally get driver mirroring
        sys.stdout = _StreamTee(wp.log_batcher, "stdout", sys.stdout)
        sys.stderr = _StreamTee(wp.log_batcher, "stderr", sys.stderr)
    try:
        wp.run()
    finally:
        try:
            wp._flush_devents()  # late direct completions still reach GCS
        except Exception:
            pass
        try:
            wp.log_batcher.stop()  # final flush before the channel drops
        except Exception:
            pass
        channel.close()


if __name__ == "__main__":
    main()
