"""Unique identifiers for objects, tasks, actors, nodes, jobs, placement groups.

Design follows the reference's ID scheme in spirit (ref: src/ray/design_docs/
id_specification.md — ids are fixed-size random byte strings with embedded
provenance), simplified: every id is 16 random bytes, hex-printable. ObjectIds
embed the creating task's id plus a return/put index so lineage can be derived
without a lookup table.
"""
from __future__ import annotations

import os
import threading

_ID_SIZE = 16

_NIL = b"\x00" * _ID_SIZE

_rand_local = threading.local()


class BaseId:
    __slots__ = ("_bytes", "_hash")
    _kind = "Id"

    def __init__(self, id_bytes: bytes):
        if not isinstance(id_bytes, bytes) or len(id_bytes) != _ID_SIZE:
            raise ValueError(f"{self._kind} requires {_ID_SIZE} bytes, got {id_bytes!r}")
        self._bytes = id_bytes

    @classmethod
    def from_random(cls):
        # os.urandom is a syscall (~100us under load): batch a page of
        # entropy per thread and slice ids from it (task-heavy drivers
        # mint thousands of ids per second)
        local = _rand_local
        buf = getattr(local, "buf", b"")
        pos = getattr(local, "pos", 0)
        if pos + _ID_SIZE > len(buf):
            buf = local.buf = os.urandom(_ID_SIZE * 256)
            pos = local.pos = 0
        local.pos = pos + _ID_SIZE
        return cls(buf[pos:pos + _ID_SIZE])

    @classmethod
    def nil(cls):
        return cls(_NIL)

    @classmethod
    def from_hex(cls, hex_str: str):
        return cls(bytes.fromhex(hex_str))

    def is_nil(self) -> bool:
        return self._bytes == _NIL

    def binary(self) -> bytes:
        return self._bytes

    def hex(self) -> str:
        return self._bytes.hex()

    def __hash__(self):
        # ids key every hot table; cache the hash (immutable value).
        try:
            return self._hash
        except AttributeError:
            h = self._hash = hash((self._kind, self._bytes))
            return h

    def __reduce__(self):
        # NEVER pickle the cached hash: bytes hashing is salted per
        # process (PYTHONHASHSEED), so a hash computed in a worker is
        # wrong in the driver — equal ids would miss every dict lookup
        return (type(self), (self._bytes,))

    def __eq__(self, other):
        return type(other) is type(self) and other._bytes == self._bytes

    def __repr__(self):
        return f"{type(self).__name__}({self._bytes.hex()[:12]})"


class JobId(BaseId):
    _kind = "Job"


class NodeId(BaseId):
    _kind = "Node"


class WorkerId(BaseId):
    _kind = "Worker"


class ActorId(BaseId):
    _kind = "Actor"


class PlacementGroupId(BaseId):
    _kind = "PlacementGroup"


class TaskId(BaseId):
    _kind = "Task"


class ObjectId(BaseId):
    """Object ids embed provenance: first 12 bytes = owning task id prefix,
    last 4 bytes = index (put or return slot). Mirrors the reference's scheme
    where ObjectIDs are computed from TaskID + index (id_specification.md)."""

    _kind = "Object"

    @classmethod
    def for_task_return(cls, task_id: TaskId, index: int) -> "ObjectId":
        return cls(task_id.binary()[:12] + index.to_bytes(4, "little"))

    @classmethod
    def for_put(cls, task_id: TaskId, put_index: int) -> "ObjectId":
        # puts use the high bit of the index to avoid clashing with returns
        return cls(task_id.binary()[:12] + (put_index | 0x8000_0000).to_bytes(4, "little"))

    def task_prefix(self) -> bytes:
        return self._bytes[:12]

    def index(self) -> int:
        return int.from_bytes(self._bytes[12:], "little")


class _Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0

    def next(self) -> int:
        with self._lock:
            self._n += 1
            return self._n


__all__ = [
    "BaseId",
    "JobId",
    "NodeId",
    "WorkerId",
    "ActorId",
    "PlacementGroupId",
    "TaskId",
    "ObjectId",
]
