"""@remote for plain functions.

Equivalent of the reference's RemoteFunction machinery
(ref: python/ray/remote_function.py:245 _remote — options resolution per
python/ray/_private/ray_option_utils.py; function pickled once per job and
exported through the GCS KV function table)."""
from __future__ import annotations

import functools
from typing import Any, Dict, List, Optional

from . import runtime as runtime_mod
from . import serialization
from .config import DEFAULT as cfg
from .object_ref import ObjectRef
from ..util.tracing import current_context as _trace_ctx
from .task_spec import (ARG_REF, ARG_VALUE, STREAMING_RETURNS,
                        SchedulingStrategy, TaskSpec,
                        TaskType)

_VALID_OPTIONS = {
    "num_cpus", "num_tpus", "resources", "num_returns", "max_retries",
    "retry_exceptions", "scheduling_strategy", "name", "memory",
    "placement_group", "placement_group_bundle_index", "runtime_env",
}


def resolve_resources(options: Dict[str, Any], default_cpus: float = 1.0) -> Dict[str, float]:
    res = dict(options.get("resources") or {})
    res["CPU"] = float(options.get("num_cpus", default_cpus))
    if options.get("num_tpus"):
        res["TPU"] = float(options["num_tpus"])
    if options.get("memory"):
        res["memory"] = float(options["memory"])
    return {k: v for k, v in res.items() if v}


def resolve_strategy(options: Dict[str, Any]) -> SchedulingStrategy:
    strat = options.get("scheduling_strategy")
    if strat is None:
        pg = options.get("placement_group")
        if pg is not None:
            return SchedulingStrategy(
                kind="PLACEMENT_GROUP", placement_group_id=pg.id,
                bundle_index=options.get("placement_group_bundle_index", -1))
        return SchedulingStrategy()
    if isinstance(strat, SchedulingStrategy):
        return strat
    if isinstance(strat, str):
        if strat == "SPREAD":
            return SchedulingStrategy(kind="SPREAD")
        if strat == "DEFAULT":
            return SchedulingStrategy()
        raise ValueError(f"Unknown scheduling strategy {strat!r}")
    # duck-typed strategy objects from util.scheduling_strategies
    return strat.to_spec()


def prepare_args(rt, args, kwargs):
    """Top-level ObjectRefs pass by reference; small plain values inline in
    the spec; large values are promoted to the object store first
    (ref: transport/dependency_resolver.cc + ray_config_def.h:516)."""
    publish = getattr(rt, "ensure_published", None)

    def one(v):
        if isinstance(v, ObjectRef):
            if publish is not None:
                # a locally-held direct result escaping into a task arg
                # must reach the head first (docs/DISPATCH.md)
                publish(v.id)
            return (ARG_REF, v)
        sobj = serialization.serialize(v)
        if sobj.total_bytes <= cfg.max_direct_call_object_size:
            if publish is not None:
                # refs NESTED in an inlined container arg escape this
                # process just like top-level ones: the executing worker
                # will deserialize and fetch them through the head
                for r in sobj.contained_refs:
                    publish(r.id)
            return (ARG_VALUE, sobj.to_bytes())
        ref = rt.put(v)
        return (ARG_REF, ref)

    return [one(a) for a in args], {k: one(v) for k, v in kwargs.items()}


class RemoteFunction:
    def __init__(self, fn, options: Optional[Dict[str, Any]] = None):
        self._fn = fn
        self._options = dict(options or {})
        for k in self._options:
            if k not in _VALID_OPTIONS:
                raise ValueError(f"Invalid @remote option {k!r}")
        self._func_ids: Dict[str, str] = {}  # runtime worker_id.hex -> func_id
        # per-runtime wire template + normalized demand: every spec this
        # function submits shares its constant fields, so they encode once
        self._wire_tmpls: Dict[str, tuple] = {}
        self._consts: Dict[str, dict] = {}
        self._norm_demand: Optional[Dict[str, float]] = None
        self._demand_key: Optional[tuple] = None
        functools.update_wrapper(self, fn)

    def options(self, **overrides) -> "RemoteFunction":
        merged = dict(self._options)
        merged.update(overrides)
        rf = RemoteFunction(self._fn, merged)
        return rf

    def remote(self, *args, **kwargs):
        rt = runtime_mod.get_runtime()
        # keyed by the runtime's unique worker id, not id(rt): a new runtime
        # allocated at a recycled address must re-export into its own GCS
        rt_key = rt.worker_id.hex()
        func_id = self._func_ids.get(rt_key)
        if func_id is None:
            func_id = rt.export_function(self._fn)
            self._func_ids[rt_key] = func_id
        sargs, skwargs = prepare_args(rt, args, kwargs)
        # constants of this (function, options, runtime) resolved once —
        # the submit loop is the head-throughput envelope's hot path
        consts = self._consts.get(rt_key)
        if consts is None:
            num_returns = self._options.get("num_returns", 1)
            if num_returns == "streaming":
                num_returns = STREAMING_RETURNS
            consts = {
                "job_id": getattr(rt, "job_id", None) or _job_of(rt),
                "description": (self._options.get("name")
                                or getattr(self._fn, "__name__", "fn")),
                "num_returns": int(num_returns),
                "resources": resolve_resources(self._options),
                "max_retries": int(self._options.get(
                    "max_retries", cfg.task_max_retries)),
                "retry_exceptions": bool(self._options.get(
                    "retry_exceptions", False)),
                "scheduling_strategy": resolve_strategy(self._options),
                "runtime_env": rt.prepare_runtime_env(
                    self._options.get("runtime_env")),
            }
            self._consts[rt_key] = consts
        spec = TaskSpec(
            task_id=rt.new_task_id(),
            task_type=TaskType.NORMAL_TASK,
            func_id=func_id,
            args=sargs,
            kwargs=skwargs,
            trace_ctx=_trace_ctx(),
            **consts,
        )
        tmpl = self._wire_tmpls.get(rt_key)
        if tmpl is None:
            from . import wire

            tmpl = wire.make_struct_template(
                spec, ("task_id", "args", "kwargs", "trace_ctx"))
            self._wire_tmpls[rt_key] = tmpl
        spec._wire_tmpl = tmpl
        if self._norm_demand is None:
            from .resources import normalize

            # publish _demand_key FIRST: a racing second submission
            # branches on _norm_demand and then reads _demand_key
            nd = normalize(spec.resources)
            self._demand_key = tuple(sorted(nd.items()))
            self._norm_demand = nd
        spec._demand = self._norm_demand
        spec._demand_key = self._demand_key
        refs = rt.submit_spec(spec)
        num_returns = consts["num_returns"]
        if num_returns == STREAMING_RETURNS:
            from .object_ref import ObjectRefGenerator

            return ObjectRefGenerator(spec.task_id, rt)
        if num_returns == 0:
            return None
        if num_returns == 1:
            return refs[0]
        return refs

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Remote function '{getattr(self._fn, '__name__', 'fn')}' cannot be "
            "called directly; use .remote().")


def _job_of(rt):
    from .ids import JobId

    return JobId.nil()
