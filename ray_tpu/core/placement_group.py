"""Placement groups — gang resource reservation with 2-phase commit.

Equivalent of the reference's placement group API
(ref: python/ray/util/placement_group.py:139 placement_group();
GCS manager + 2PC in src/ray/gcs/gcs_server/gcs_placement_group_manager.cc,
raylet side src/ray/raylet/placement_group_resource_manager.cc).

TPU-native note: bundles may request `TPU` and carry a `tpu_slice` label so a
STRICT_SPREAD group maps one bundle per pod host — this is how MeshGroup gang
schedules its per-host workers (ray_tpu/parallel/mesh_group.py)."""
from __future__ import annotations

from typing import Dict, List, Optional

from . import runtime as runtime_mod
from .ids import PlacementGroupId


class PlacementGroup:
    def __init__(self, pg_id: PlacementGroupId, bundles: List[Dict[str, float]],
                 strategy: str):
        self.id = pg_id
        self.bundle_specs = bundles
        self.strategy = strategy

    def ready(self, timeout: float = 30.0) -> bool:
        rt = runtime_mod.get_runtime()
        return rt.pg_ready(self.id, timeout)

    def wait(self, timeout_seconds: float = 30.0) -> bool:
        return self.ready(timeout_seconds)

    @property
    def bundle_count(self) -> int:
        return len(self.bundle_specs)

    def __reduce__(self):
        return (PlacementGroup, (self.id, self.bundle_specs, self.strategy))


def placement_group(bundles: List[Dict[str, float]], strategy: str = "PACK",
                    name: str = "") -> PlacementGroup:
    if strategy not in ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD"):
        raise ValueError(f"Invalid placement strategy {strategy!r}")
    if not bundles:
        raise ValueError("bundles must be non-empty")
    rt = runtime_mod.get_runtime()
    pg_id = rt.create_placement_group(bundles, strategy, name)
    return PlacementGroup(pg_id, bundles, strategy)


def remove_placement_group(pg: PlacementGroup) -> None:
    rt = runtime_mod.get_runtime()
    rt.remove_placement_group(pg.id)


def placement_group_table() -> List[dict]:
    rt = runtime_mod.get_runtime()
    if not hasattr(rt, "gcs"):
        raise RuntimeError("placement_group_table is driver-only")
    return [
        {"placement_group_id": i.pg_id.hex(), "state": i.state,
         "strategy": i.strategy, "bundles": i.bundles, "name": i.name}
        for i in rt.gcs.list_pgs()
    ]
