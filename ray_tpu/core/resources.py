"""Resource-set algebra.

Equivalent of the reference's scheduling primitives
(ref: src/ray/common/scheduling/cluster_resource_data.h NodeResources /
ResourceRequest; fixed_point.h). Floating resources are kept as floats with an
epsilon — the fixed-point trick is unnecessary at this scale. `TPU` and
`tpu_slice` are first-class resource names so the scheduler can gang-place
mesh workers onto slice topologies.
"""
from __future__ import annotations

from typing import Dict

EPS = 1e-9

ResourceSet = Dict[str, float]


def res_add(a: ResourceSet, b: ResourceSet) -> ResourceSet:
    out = dict(a)
    for k, v in b.items():
        out[k] = out.get(k, 0.0) + v
    return out


def res_sub(a: ResourceSet, b: ResourceSet) -> ResourceSet:
    out = dict(a)
    for k, v in b.items():
        out[k] = out.get(k, 0.0) - v
    return out


def res_ge(a: ResourceSet, b: ResourceSet) -> bool:
    """a >= b elementwise (a can satisfy demand b)."""
    for k, v in b.items():
        if v > EPS and a.get(k, 0.0) + EPS < v:
            return False
    return True


def res_nonneg(a: ResourceSet) -> bool:
    return all(v >= -EPS for v in a.values())


def normalize(a: ResourceSet) -> ResourceSet:
    return {k: float(v) for k, v in a.items() if abs(v) > EPS}
