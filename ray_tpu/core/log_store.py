"""Head-side attributed log store — the GCS log plane.

Equivalent of the reference's log aggregation surface (ref:
dashboard/modules/log/log_manager.py + the `ray logs` state API): every
worker's stdout/stderr/structured-log lines arrive as attributed records
and land here, indexed by job/task/actor/worker/node, under a byte
budget (oldest-first eviction, counted). Readers page with a monotonic
``cursor`` and can *follow*: a query with ``follow_timeout`` long-polls
on a condition variable until matching records arrive — the primitive
under ``ray_tpu logs --follow`` and the dashboard's live log tab.

Record schema (all values wire-primitive)::

    {ts, node_id, worker_id, pid, job_id, task_id, actor_id,
     stream, level, seq, line}
"""
from __future__ import annotations

import threading
from collections import deque
from typing import Any, Dict, List, Optional

# accounting overhead per record beyond the line text (dict + id hexes)
_REC_OVERHEAD = 160

_ERROR_LEVELS = ("WARNING", "ERROR", "CRITICAL", "FATAL")


class LogStore:
    def __init__(self, max_bytes: int = 16 * 1024 * 1024):
        self._max_bytes = int(max_bytes)
        self._cv = threading.Condition()
        self._recs: deque = deque()
        self._base = 0          # cursor of _recs[0]
        self._bytes = 0
        self.total_lines = 0
        self.evicted_lines = 0

    # ---- ingest --------------------------------------------------------------

    def append(self, records: List[Dict[str, Any]]) -> None:
        if not records:
            return
        with self._cv:
            for rec in records:
                self._recs.append(rec)
                self._bytes += len(rec.get("line", "")) + _REC_OVERHEAD
                self.total_lines += 1
            while self._bytes > self._max_bytes and self._recs:
                old = self._recs.popleft()
                self._base += 1
                self._bytes -= len(old.get("line", "")) + _REC_OVERHEAD
                self.evicted_lines += 1
            self._cv.notify_all()

    # ---- queries -------------------------------------------------------------

    @staticmethod
    def _matches(rec: Dict[str, Any],
                 job_id: Optional[str], task_id: Optional[str],
                 actor_id: Optional[str], worker_id: Optional[str],
                 node_id: Optional[str], stream: Optional[str],
                 errors_only: bool) -> bool:
        # id filters match on hex prefixes (CLI ergonomics, like the
        # reference's state API)
        if job_id and not str(rec.get("job_id", "")).startswith(job_id):
            return False
        if task_id and not str(rec.get("task_id", "")).startswith(task_id):
            return False
        if actor_id and not str(rec.get("actor_id", "")).startswith(actor_id):
            return False
        if worker_id and not str(rec.get("worker_id", "")).startswith(
                worker_id):
            return False
        if node_id and not str(rec.get("node_id", "")).startswith(node_id):
            return False
        if stream and rec.get("stream") != stream:
            return False
        if errors_only and rec.get("stream") != "stderr" \
                and rec.get("level", "") not in _ERROR_LEVELS:
            return False
        return True

    def query(self, job_id: Optional[str] = None,
              task_id: Optional[str] = None,
              actor_id: Optional[str] = None,
              worker_id: Optional[str] = None,
              node_id: Optional[str] = None,
              stream: Optional[str] = None,
              errors_only: bool = False,
              since: Optional[int] = None,
              limit: int = 500,
              follow_timeout: Optional[float] = None) -> Dict[str, Any]:
        """-> {"records": [...], "cursor": next_since}.

        ``since`` is the cursor returned by the previous call (records at
        positions >= since are scanned); with ``follow_timeout`` the call
        long-polls until a matching record lands past ``since`` or the
        timeout expires. Without ``since``, the newest ``limit`` matches
        are returned (tail semantics)."""
        import itertools as _it
        import time as _time

        limit = max(1, int(limit))
        deadline = (None if not follow_timeout
                    else _time.monotonic() + float(follow_timeout))
        while True:
            # snapshot under the lock, FILTER OUTSIDE it: a sparse filter
            # over a full store must not stall every ingest for its scan
            with self._cv:
                base = self._base
                if since is None:
                    recs = list(self._recs)
                    start = base
                else:
                    start = max(base, int(since))
                    recs = list(_it.islice(self._recs, start - base,
                                           None))
                tail = base + len(self._recs)
            out: List[Dict[str, Any]] = []
            if since is None:
                # tail semantics: newest matches first, restore order
                cursor = tail
                for rec in reversed(recs):
                    if self._matches(rec, job_id, task_id, actor_id,
                                     worker_id, node_id, stream,
                                     errors_only):
                        out.append(rec)
                        if len(out) >= limit:
                            break
                out.reverse()
            else:
                # paging: when the limit cuts the scan short, the cursor
                # points at the NEXT unscanned record — a follower never
                # skips the remainder of a burst
                cursor = tail
                for i, rec in enumerate(recs):
                    if self._matches(rec, job_id, task_id, actor_id,
                                     worker_id, node_id, stream,
                                     errors_only):
                        out.append(rec)
                        if len(out) >= limit:
                            cursor = start + i + 1
                            break
            if out or deadline is None:
                return {"records": out, "cursor": cursor}
            remaining = deadline - _time.monotonic()
            if remaining <= 0:
                return {"records": out, "cursor": cursor}
            # everything up to `tail` was judged non-matching; sleep
            # until new records land (re-check under the lock so a
            # record that arrived after the snapshot is not missed)
            since = tail
            with self._cv:
                if self._base + len(self._recs) == tail:
                    self._cv.wait(remaining)

    def stats(self) -> Dict[str, int]:
        with self._cv:
            return {"lines": len(self._recs), "bytes": self._bytes,
                    "total_lines": self.total_lines,
                    "evicted_lines": self.evicted_lines,
                    "cursor": self._base + len(self._recs)}
