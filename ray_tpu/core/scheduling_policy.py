"""Cluster scheduling policies.

Equivalent of the reference's policy suite
(ref: src/ray/raylet/scheduling/policy/hybrid_scheduling_policy.h:50 —
pack-until-threshold-then-spread with spread threshold 0.5 from
ray_config_def.h:193; spread_scheduling_policy.cc; node_affinity_...;
bundle_scheduling_policy.cc for PACK/SPREAD/STRICT_PACK/STRICT_SPREAD;
composed via composite_scheduling_policy.h:32).
"""
from __future__ import annotations

import random
from dataclasses import dataclass
from typing import Dict, List, Optional

from .ids import NodeId
from .resources import ResourceSet, res_ge, res_sub
from .task_spec import SchedulingStrategy


@dataclass
class NodeView:
    node_id: NodeId
    total: ResourceSet
    available: ResourceSet
    alive: bool = True
    # labels, e.g. {"tpu_slice": "v5e-16-0", "host": "..."}
    labels: Dict[str, str] = None


def _utilization(view: NodeView) -> float:
    """Max utilization across resource dimensions the node actually has."""
    util = 0.0
    for k, total in view.total.items():
        if total > 0:
            used = total - view.available.get(k, 0.0)
            util = max(util, used / total)
    return util


def _feasible(view: NodeView, demand: ResourceSet) -> bool:
    return view.alive and res_ge(view.total, demand)


def _has_available(view: NodeView, demand: ResourceSet) -> bool:
    return view.alive and res_ge(view.available, demand)


class Scheduler:
    """Picks a node for a resource demand + strategy. The caller holds the
    authoritative per-node availability (cluster view fed by the syncer)."""

    def __init__(self, spread_threshold: float = 0.5, seed: int = 0):
        self.spread_threshold = spread_threshold
        self._rr_counter = 0
        self._rng = random.Random(seed)

    def pick_node(
        self,
        views: List[NodeView],
        demand: ResourceSet,
        strategy: SchedulingStrategy,
        local_node_id: Optional[NodeId] = None,
        locality: Optional[Dict[NodeId, int]] = None,
    ) -> Optional[NodeId]:
        if strategy.kind == "NODE_AFFINITY":
            target = next((v for v in views if v.node_id == strategy.node_id), None)
            if target is not None and _has_available(target, demand):
                return target.node_id
            if strategy.soft:
                return self._hybrid(views, demand, local_node_id)
            if target is not None and _feasible(target, demand):
                return target.node_id  # queue on that node until resources free
            return None
        if strategy.kind == "SPREAD":
            return self._spread(views, demand)
        # Locality-aware default policy (ref: core_worker/lease_policy.cc
        # LocalityAwareLeasePolicy::GetBestNodeForTask — request the lease
        # from the node holding the most argument bytes): a node already
        # holding the args skips one or two DCN hops per argument. Only a
        # node that can run the task NOW wins on locality; otherwise fall
        # through to hybrid packing.
        if locality:
            ranked = sorted(
                (v for v in views
                 if locality.get(v.node_id) and _has_available(v, demand)),
                key=lambda v: -locality[v.node_id])
            if ranked:
                return ranked[0].node_id
        return self._hybrid(views, demand, local_node_id)

    # -- hybrid: pack onto low-utilization nodes (local first) until the
    # spread threshold, then prefer least-utilized (ref: hybrid_scheduling_policy.h:61)
    def _hybrid(self, views: List[NodeView], demand: ResourceSet,
                local_node_id: Optional[NodeId]) -> Optional[NodeId]:
        avail = [v for v in views if _has_available(v, demand)]
        if avail:
            ordered = sorted(
                avail,
                key=lambda v: (
                    _utilization(v) >= self.spread_threshold,  # under-threshold first
                    _utilization(v),
                    v.node_id != local_node_id,  # prefer local among ties
                    v.node_id.hex(),
                ),
            )
            # pack: among under-threshold nodes prefer the *most* utilized
            under = [v for v in ordered if _utilization(v) < self.spread_threshold]
            if under:
                return max(under, key=lambda v: (_utilization(v), v.node_id == local_node_id)).node_id
            return ordered[0].node_id
        feas = [v for v in views if _feasible(v, demand)]
        if feas:
            # infeasible now but possible later: queue on least loaded feasible node
            return min(feas, key=_utilization).node_id
        return None

    def _spread(self, views: List[NodeView], demand: ResourceSet) -> Optional[NodeId]:
        avail = [v for v in views if _has_available(v, demand)]
        pool = avail or [v for v in views if _feasible(v, demand)]
        if not pool:
            return None
        pool = sorted(pool, key=lambda v: v.node_id.hex())
        self._rr_counter += 1
        return pool[self._rr_counter % len(pool)].node_id

    # -- placement-group bundle packing (ref: bundle_scheduling_policy.cc) -----

    def pick_bundle_nodes(
        self,
        views: List[NodeView],
        bundles: List[ResourceSet],
        strategy: str,
    ) -> Optional[List[NodeId]]:
        """Return one node per bundle, or None if unschedulable."""
        views = [v for v in views if v.alive]
        remaining = {v.node_id: dict(v.available) for v in views}

        def fits(nid, bundle):
            return res_ge(remaining[nid], bundle)

        def take(nid, bundle):
            remaining[nid] = res_sub(remaining[nid], bundle)

        order = sorted(views, key=lambda v: v.node_id.hex())
        result: List[NodeId] = []
        if strategy in ("STRICT_PACK",):
            for v in order:
                if all(res_ge_acc(remaining[v.node_id], bundles)):
                    return [v.node_id] * len(bundles)
            # try exact accumulation per node
            for v in order:
                acc = dict(remaining[v.node_id])
                ok = True
                for b in bundles:
                    if not res_ge(acc, b):
                        ok = False
                        break
                    acc = res_sub(acc, b)
                if ok:
                    return [v.node_id] * len(bundles)
            return None
        if strategy == "STRICT_SPREAD":
            used_nodes = set()
            placed_strict: List[Optional[NodeId]] = [None] * len(bundles)
            # place largest bundles first, but keep bundle-index alignment
            for i, b in sorted(enumerate(bundles), key=lambda kv: -sum(kv[1].values())):
                cand = [v for v in order
                        if v.node_id not in used_nodes and fits(v.node_id, b)]
                if not cand:
                    return None
                nid = cand[0].node_id
                used_nodes.add(nid)
                take(nid, b)
                placed_strict[i] = nid
            return placed_strict  # type: ignore[return-value]
        # PACK (best-effort pack) / SPREAD (best-effort spread)
        prefer_spread = strategy == "SPREAD"
        placed: List[Optional[NodeId]] = [None] * len(bundles)
        for i, b in sorted(enumerate(bundles), key=lambda kv: -sum(kv[1].values())):
            cand = [v for v in order if fits(v.node_id, b)]
            if not cand:
                return None
            if prefer_spread:
                counts = {v.node_id: sum(1 for p in placed if p == v.node_id) for v in cand}
                nid = min(cand, key=lambda v: (counts[v.node_id], v.node_id.hex())).node_id
            else:
                counts = {v.node_id: sum(1 for p in placed if p == v.node_id) for v in cand}
                nid = max(cand, key=lambda v: (counts[v.node_id], -int(v.node_id.hex(), 16) % 997)).node_id
            placed[i] = nid
            take(nid, b)
        return placed  # type: ignore[return-value]


def res_ge_acc(avail: ResourceSet, bundles: List[ResourceSet]):
    acc = dict(avail)
    for b in bundles:
        yield res_ge(acc, b)
        acc = res_sub(acc, b)
