"""Per-node shared-memory object store + per-process memory store.

Equivalent of the reference's plasma store + core-worker memory store
(ref: src/ray/object_manager/plasma/store.h:55 ObjectLifecycleManager,
eviction_policy.h LRUCache, create_request_queue.h backpressure;
src/ray/core_worker/store_provider/memory_store/ for small objects).

TPU-host design: one store per node; each sealed object lives in its own
POSIX shared-memory segment (mmap) so any process on the host maps it
zero-copy. Creation follows the plasma protocol shape: clients ask the store
to create (reserving capacity, may trigger LRU eviction or disk spill), write
into the mapped buffer, then seal. Primary copies are pinned (not evictable)
until the owner releases them; unpinned copies are LRU-evicted or spilled to
disk under memory pressure (ref: src/ray/raylet/local_object_manager.h:41).

The C++ store (ray_tpu/native/store.cpp, wrapped by NativePlasmaStore
below) plugs in behind the same interface when the toolchain can build
it — `make_store` picks it by default; this Python implementation is the
always-available fallback and the semantics reference.
"""
from __future__ import annotations

import mmap
import os
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from multiprocessing import shared_memory
from typing import Dict, Optional

from ..devtools.locks import instrumented_lock
from ..exceptions import ObjectStoreFullError
from ..util import metrics as _metrics
from .ids import NodeId, ObjectId
from .serialization import SerializedObject

# store-op latency + transfer volume (ref: the reference's plasma store
# and object_manager stats). In worker/agent processes these live in the
# local registry and ship to the head node-tagged via metrics_push /
# heartbeat piggyback.
_H_STORE_OP = _metrics.Histogram(
    "ray_tpu_object_store_op_seconds",
    "shared-memory store operation latency",
    boundaries=_metrics.FAST_BOUNDARIES, tag_keys=("op",))
_C_TRANSFER_BYTES = _metrics.Counter(
    "ray_tpu_object_transfer_bytes_total",
    "bytes moved by store puts/gets and inter-node pulls",
    tag_keys=("op",))


def _observe_op(op: str, t0: float, nbytes: Optional[int] = None) -> None:
    _H_STORE_OP.observe(time.perf_counter() - t0, tags={"op": op})
    if nbytes:
        _C_TRANSFER_BYTES.inc(nbytes, tags={"op": op})


# Note on resource tracking: only the driver process creates SharedMemory
# segments (workers attach via /dev/shm mmap — see SegmentReader), so the
# stock resource_tracker bookkeeping is already balanced: __init__ registers,
# unlink() unregisters, and a crashed driver leaves the tracker to clean up.


@dataclass
class _Entry:
    shm: Optional[shared_memory.SharedMemory]
    size: int
    sealed: bool = False
    pinned: bool = False
    spilled_path: Optional[str] = None
    created_at: float = field(default_factory=time.monotonic)



def _assemble_chunk(partial, object_id, offset, total, data,
                    create, write, finish) -> bool:
    """Shared chunked-push state machine for both store classes. Chunks
    must arrive in order; offset 0 RESTARTS the object (a caller retrying
    a failed push from scratch must not inherit a stale byte counter and
    seal with an unwritten tail). Returns True when the object seals."""
    if offset == 0:
        create()
        partial[object_id] = 0
    expect = partial.get(object_id)
    if expect is None or offset != expect:
        raise ValueError(
            f"out-of-order chunk for {object_id.hex()[:12]}: "
            f"offset {offset}, expected {expect}")
    write(offset, data)
    partial[object_id] = offset + len(data)
    if partial[object_id] >= total:
        del partial[object_id]
        finish()
        return True
    return False


class PlasmaStore:
    """Host shared-memory store for one (possibly simulated) node."""

    def __init__(self, node_id: NodeId, capacity_bytes: int, spill_dir: str = "",
                 min_spilling_size: int = 1024 * 1024):
        self._node_id = node_id
        self._prefix = f"rtpu{node_id.hex()[:10]}"
        self._capacity = capacity_bytes
        self._min_spilling_size = min_spilling_size
        self._used = 0
        self._lock = instrumented_lock("object_store", reentrant=True)
        self._partial: Dict[ObjectId, int] = {}  # chunked-push progress
        self._entries: "OrderedDict[ObjectId, _Entry]" = OrderedDict()
        self._spill_dir = spill_dir
        # external storage tier: an fsspec URL ("s3://...", "gs://...",
        # "memory://...") spills over the network instead of local disk
        # (ref: python/ray/_private/external_storage.py:72 — there via
        # smart_open; here the same fsspec machinery as tune/syncer.py)
        self._spill_fs = None
        self._spill_root = ""
        if spill_dir and "://" in spill_dir:
            from ..util.fs import split_fs_url

            self._spill_fs, self._spill_root = split_fs_url(spill_dir)
            try:
                self._spill_fs.makedirs(self._spill_root, exist_ok=True)
            except Exception:
                pass
            spill_dir = ""  # no local mkdir below
        self._destroyed = False
        if spill_dir:
            os.makedirs(spill_dir, exist_ok=True)
        self.num_evictions = 0
        self.num_spills = 0
        self._channels: set = set()  # live compiled-graph channel segments

    # -- plasma protocol: create -> write -> seal ------------------------------

    def segment_name(self, object_id: ObjectId) -> str:
        return f"{self._prefix}_{object_id.hex()}"

    def create(self, object_id: ObjectId, size: int) -> str:
        """Reserve capacity and create the segment; returns the shm name the
        client should attach to and write into. Raises ObjectStoreFullError if
        space cannot be made (create-queue backpressure is handled by caller)."""
        with self._lock:
            if object_id in self._entries:
                # idempotent re-create: lineage reconstruction may re-run the
                # producing task while a stale entry lingers
                self._release_entry(self._entries.pop(object_id))
            self._ensure_space(size)
            name = self.segment_name(object_id)
            try:
                shm = shared_memory.SharedMemory(name=name, create=True, size=max(size, 1))
            except FileExistsError:
                # stale segment from a previous run; reclaim it
                stale = shared_memory.SharedMemory(name=name)
                stale.close()
                stale.unlink()
                shm = shared_memory.SharedMemory(name=name, create=True, size=max(size, 1))
            self._entries[object_id] = _Entry(shm=shm, size=size)
            self._used += size
            return name

    def seal(self, object_id: ObjectId) -> None:
        with self._lock:
            e = self._entries[object_id]
            e.sealed = True
            self._entries.move_to_end(object_id)

    def put_serialized(self, object_id: ObjectId, sobj: SerializedObject,
                       pin: bool = True) -> None:
        """Create+write+seal in one step (server-local fast path)."""
        t0 = time.perf_counter()
        # hold the (reentrant) lock across create->write->seal: a
        # concurrent create's eviction pass must not drop the entry
        # mid-write (same discipline as the native store's put path)
        with self._lock:
            self.create(object_id, sobj.total_bytes)
            e = self._entries[object_id]
            sobj.write_into(memoryview(e.shm.buf))
            e.pinned = pin
            self.seal(object_id)
        _observe_op("put", t0, sobj.total_bytes)

    def put_bytes(self, object_id: ObjectId, data: bytes, pin: bool = True) -> None:
        t0 = time.perf_counter()
        with self._lock:  # see put_serialized: write under the lock
            self.create(object_id, len(data))
            e = self._entries[object_id]
            e.shm.buf[: len(data)] = data
            e.pinned = pin
            self.seal(object_id)
        _observe_op("put", t0, len(data))

    def put_chunk(self, object_id: ObjectId, offset: int, total: int,
                  data: bytes, pin: bool = True) -> bool:
        """Incremental create->write->seal for chunked pushes (the head's
        remote-put path; mirror of read_store_chunk on the pull side).
        Returns True when the final chunk seals the object."""
        with self._lock:
            def finish():
                e = self._entries[object_id]
                e.pinned = pin
                self.seal(object_id)

            return _assemble_chunk(
                self._partial, object_id, offset, total, data,
                create=lambda: self.create(object_id, total),
                write=lambda off, d: self._entries[object_id].shm.buf
                .__setitem__(slice(off, off + len(d)), d),
                finish=finish)

    # -- compiled-graph channels (ray_tpu/cgraph) ------------------------------
    # A channel is a pre-allocated single-slot segment reused for the life
    # of a compiled graph: created once at compile time, written/read in
    # place by the producer/consumer processes (never sealed — sealing
    # means immutable), pinned so neither eviction nor spilling can touch
    # it, and released by teardown. Backpressure comes from slot occupancy
    # in the channel header (cgraph/channel.py), not from store capacity.

    def allocate_channel(self, channel_id: ObjectId, size: int) -> str:
        """Reserve a mutable, pinned segment for a compiled-graph channel;
        returns the shm name both endpoints attach to."""
        with self._lock:
            name = self.create(channel_id, size)
            e = self._entries[channel_id]
            e.pinned = True  # belt: unsealed entries are already
            # invisible to the LRU/spill scans, which require sealed
            self._channels.add(channel_id)
            return name

    def release_channel(self, channel_id: ObjectId) -> None:
        """Teardown: unlink the channel segment and return its capacity.
        Attached readers keep their mapping until they release it (POSIX
        unlink semantics), so a racing in-flight read cannot fault."""
        with self._lock:
            self._channels.discard(channel_id)
            self.delete(channel_id)

    # -- reads -----------------------------------------------------------------

    def contains(self, object_id: ObjectId) -> bool:
        with self._lock:
            e = self._entries.get(object_id)
            return e is not None and e.sealed

    def get_bytes(self, object_id: ObjectId) -> Optional[bytes]:
        """Copy out the object payload (used for inter-node transfer and
        restore; local readers should attach to the segment instead)."""
        with self._lock:
            e = self._entries.get(object_id)
            if e is None:
                return None
            if e.shm is None:
                return self._read_spilled(e)
            self._entries.move_to_end(object_id)
            return bytes(e.shm.buf[: e.size])

    def object_size(self, object_id: ObjectId) -> Optional[int]:
        with self._lock:
            e = self._entries.get(object_id)
            return None if e is None else e.size

    def get_segment(self, object_id: ObjectId) -> Optional[tuple[str, int]]:
        """Return (shm_name, size) for zero-copy local access; restores a
        spilled object back into shared memory first if needed."""
        t0 = time.perf_counter()
        with self._lock:
            e = self._entries.get(object_id)
            if e is None or not e.sealed:
                return None
            if e.shm is None:  # spilled: restore
                data = self._read_spilled(e)
                if data is None:
                    # external spill copy lost/unreachable: report the
                    # object missing (lineage recovery's signal) rather
                    # than poisoning the entry with a half-made segment
                    return None
                self._ensure_space(e.size)
                shm = shared_memory.SharedMemory(
                    name=self.segment_name(object_id), create=True, size=max(e.size, 1))
                shm.buf[: e.size] = data
                e.shm = shm
                self._used += e.size
            self._entries.move_to_end(object_id)
            size = e.size
        _observe_op("get", t0, size)
        return self.segment_name(object_id), size

    # -- lifetime --------------------------------------------------------------

    def pin(self, object_id: ObjectId) -> None:
        with self._lock:
            if object_id in self._entries:
                self._entries[object_id].pinned = True

    def unpin(self, object_id: ObjectId) -> None:
        with self._lock:
            if object_id in self._entries:
                self._entries[object_id].pinned = False

    def delete(self, object_id: ObjectId) -> None:
        with self._lock:
            e = self._entries.pop(object_id, None)
            if e is None:
                return
            self._release_entry(e)

    def _release_entry(self, e: _Entry) -> None:
        if e.shm is not None:
            self._used -= e.size
            try:
                e.shm.close()
                e.shm.unlink()
            except FileNotFoundError:
                pass
        if e.spilled_path:
            if self._spill_fs is not None:
                try:
                    self._spill_fs.rm(e.spilled_path)
                except Exception:
                    pass  # remote tier cleanup is best-effort
            else:
                try:
                    os.unlink(e.spilled_path)
                except FileNotFoundError:
                    pass  # anything else (EPERM, EROFS) must surface

    def _ensure_space(self, size: int) -> None:
        if size > self._capacity:
            raise ObjectStoreFullError(
                f"Object of {size} bytes exceeds store capacity {self._capacity}")
        while self._used + size > self._capacity:
            victim = None
            spill_only = False
            for oid, e in self._entries.items():  # LRU order
                if e.sealed and not e.pinned and e.shm is not None:
                    victim = (oid, e)
                    break
            if victim is None and self._spill_dir:
                # second pass: PINNED primaries may spill (never evict) —
                # the data survives in the spill tier and restores on
                # access. This is what spilling is FOR in the reference
                # (local_object_manager.cc spills pinned primary copies
                # when memory pressure demands it).
                for oid, e in self._entries.items():
                    if e.sealed and e.shm is not None \
                            and e.size >= self._min_spilling_size:
                        victim = (oid, e)
                        spill_only = True
                        break
            if victim is None:
                raise ObjectStoreFullError(
                    f"Store full ({self._used}/{self._capacity} bytes) and no "
                    f"evictable objects (all pinned)")
            oid, e = victim
            # large objects are worth a disk write (restorable later); small
            # ones are simply evicted — their owner can reconstruct
            # (ref: min_spilling_size, local_object_manager.h:110)
            if self._spill_dir and e.size >= self._min_spilling_size:
                if not self._spill(oid, e) and spill_only:
                    # spill tier failed for a PINNED primary: its bytes
                    # must not be dropped — surface the pressure
                    raise ObjectStoreFullError(
                        f"Store full and spill tier unavailable for "
                        f"pinned {oid.hex()[:12]}")
            else:
                self._evict(oid, e)

    def _spill(self, oid: ObjectId, e: _Entry) -> bool:
        """-> True if the bytes landed in the spill tier (False = the
        entry was evicted instead; only legal for unpinned copies)."""
        name = f"{self._prefix}_{oid.hex()}"
        if self._spill_fs is not None:
            path = f"{self._spill_root}/{name}"
            try:
                with self._spill_fs.open(path, "wb") as f:
                    f.write(bytes(e.shm.buf[: e.size]))
            except Exception:
                # unreachable external storage: evict instead — the
                # owner reconstructs via lineage (failure path, tested)
                if not e.pinned:
                    self._evict(oid, e)
                return False
        else:
            path = os.path.join(self._spill_dir, name)
            with open(path, "wb") as f:
                f.write(e.shm.buf[: e.size])
        e.spilled_path = path
        e.shm.close()
        e.shm.unlink()
        e.shm = None
        self._used -= e.size
        self.num_spills += 1
        return True

    def _evict(self, oid: ObjectId, e: _Entry) -> None:
        self._entries.pop(oid)
        self._release_entry(e)
        self.num_evictions += 1

    def _read_spilled(self, e: _Entry) -> Optional[bytes]:
        if not e.spilled_path:
            return None
        if self._spill_fs is not None:
            try:
                with self._spill_fs.open(e.spilled_path, "rb") as f:
                    return f.read()
            except Exception:
                return None  # external copy gone: surfaces as object lost
        with open(e.spilled_path, "rb") as f:
            return f.read()

    def stats(self) -> dict:
        with self._lock:
            return {
                "capacity": self._capacity,
                "used": self._used,
                "num_objects": len(self._entries),
                "num_evictions": self.num_evictions,
                "num_spills": self.num_spills,
                "num_channels": len(self._channels),
            }

    def destroy(self) -> None:
        """Unlink every segment — simulates node loss for chaos tests."""
        with self._lock:
            if self._destroyed:
                return
            self._destroyed = True
            for e in self._entries.values():
                self._release_entry(e)
            self._entries.clear()
            self._used = 0


class NativePlasmaStore:
    """PlasmaStore surface over the C++ core (ray_tpu/native/store.cpp):
    segment lifecycle, LRU/spill/evict decisions, capacity accounting and
    crc32c seal checksums run native; Python only moves payload bytes
    through zero-copy memoryviews of the C++-owned mappings. Same
    file-per-object /dev/shm layout, so SegmentReader and the transfer
    protocol are untouched."""

    def __init__(self, lib, node_id: NodeId, capacity_bytes: int,
                 spill_dir: str = "", min_spilling_size: int = 1024 * 1024):
        self._lib = lib
        self._node_id = node_id
        self._prefix = f"rtpu{node_id.hex()[:10]}"
        if spill_dir:
            os.makedirs(spill_dir, exist_ok=True)
        self._h = lib.rtpu_store_open(self._prefix.encode(),
                                      capacity_bytes,
                                      spill_dir.encode() or None,
                                      min_spilling_size)
        self._destroyed = False
        self._lock = instrumented_lock("object_store.native", reentrant=True)
        self._partial: Dict[ObjectId, int] = {}  # chunked-push progress
        self._channels: set = set()  # live compiled-graph channel segments

    def segment_name(self, object_id: ObjectId) -> str:
        return f"{self._prefix}_{object_id.hex()}"

    def _view(self, object_id: ObjectId):
        import ctypes

        if self._h is None:  # destroyed (simulated node death)
            return None, 0, False
        ptr = ctypes.c_void_p()
        size = ctypes.c_uint64()
        sealed = ctypes.c_int()
        rc = self._lib.rtpu_store_get(self._h, object_id.hex().encode(),
                                      ctypes.byref(ptr), ctypes.byref(size),
                                      ctypes.byref(sealed))
        if rc != 0:
            return None, 0, False
        n = size.value
        buf = (ctypes.c_char * max(n, 1)).from_address(ptr.value)
        return memoryview(buf).cast("B")[:n], n, bool(sealed.value)

    # -- plasma protocol ---------------------------------------------------

    def create(self, object_id: ObjectId, size: int) -> str:
        with self._lock:
            if self._h is None:
                raise ObjectStoreFullError("store destroyed")
            rc = self._lib.rtpu_store_create(self._h,
                                             object_id.hex().encode(), size)
        if rc == -1:
            raise ObjectStoreFullError(
                f"Object of {size} bytes exceeds store capacity")
        if rc != 0:
            raise ObjectStoreFullError(
                "Store full and no evictable objects (all pinned)")
        return self.segment_name(object_id)

    def _call(self, fn, *args) -> int:
        with self._lock:
            if self._h is None:
                return -1
            return fn(self._h, *args)

    def seal(self, object_id: ObjectId) -> None:
        self._call(self._lib.rtpu_store_seal, object_id.hex().encode(), 1)

    def put_serialized(self, object_id: ObjectId, sobj: SerializedObject,
                       pin: bool = True) -> None:
        # the whole create->write->seal sequence runs under the store
        # lock: a concurrent delete/destroy/re-create of the same oid
        # would munmap the segment mid-write and the ctypes view write
        # would SIGSEGV (the Python store fails safe via BufferError;
        # the native mapping has no such guard)
        t0 = time.perf_counter()
        with self._lock:
            self.create(object_id, sobj.total_bytes)
            mv, _, _ = self._view(object_id)
            sobj.write_into(mv)
            del mv
            if pin:
                self.pin(object_id)
            self.seal(object_id)
        _observe_op("put", t0, sobj.total_bytes)

    def put_chunk(self, object_id: ObjectId, offset: int, total: int,
                  data: bytes, pin: bool = True) -> bool:
        """Chunked create->write->seal (native-store mirror of the Python
        store's put_chunk; the RLock makes nested create/pin/seal safe)."""
        with self._lock:
            def write(off, d):
                mv, _n, _sealed = self._view(object_id)
                mv[off:off + len(d)] = d
                del mv

            def finish():
                if pin:
                    self.pin(object_id)
                self.seal(object_id)

            return _assemble_chunk(
                self._partial, object_id, offset, total, data,
                create=lambda: self.create(object_id, total),
                write=write, finish=finish)

    def put_bytes(self, object_id: ObjectId, data: bytes,
                  pin: bool = True) -> None:
        t0 = time.perf_counter()
        with self._lock:  # see put_serialized: write under the lock
            self.create(object_id, len(data))
            mv, _, _ = self._view(object_id)
            mv[:len(data)] = data
            del mv
            if pin:
                self.pin(object_id)
            self.seal(object_id)
        _observe_op("put", t0, len(data))

    # -- compiled-graph channels (same contract as PlasmaStore's) ----------

    def allocate_channel(self, channel_id: ObjectId, size: int) -> str:
        with self._lock:
            name = self.create(channel_id, size)
            self.pin(channel_id)  # channels must never evict or spill
            self._channels.add(channel_id)
            return name

    def release_channel(self, channel_id: ObjectId) -> None:
        with self._lock:
            self._channels.discard(channel_id)
            self.delete(channel_id)

    # -- reads -------------------------------------------------------------

    def contains(self, object_id: ObjectId) -> bool:
        return self._call(self._lib.rtpu_store_contains,
                          object_id.hex().encode()) == 1

    def get_bytes(self, object_id: ObjectId) -> Optional[bytes]:
        with self._lock:
            mv, n, _ = self._view(object_id)
            if mv is None:
                return None
            out = bytes(mv[:n])
            del mv
            return out

    def get_segment(self, object_id: ObjectId) -> Optional[tuple]:
        t0 = time.perf_counter()
        with self._lock:
            mv, n, sealed = self._view(object_id)  # restores spilled
            if mv is None or not sealed:
                return None
            del mv
        _observe_op("get", t0, n)
        return self.segment_name(object_id), n

    def object_size(self, object_id: ObjectId) -> Optional[int]:
        with self._lock:
            mv, n, _ = self._view(object_id)
            if mv is None:
                return None
            del mv
            return n

    def verify(self, object_id: ObjectId) -> Optional[bool]:
        """crc32c integrity check of a sealed in-memory object: True ok,
        False CORRUPTED, None unknown/spilled."""
        rc = self._call(self._lib.rtpu_store_verify,
                        object_id.hex().encode())
        return None if rc < 0 else bool(rc)

    # -- lifetime ----------------------------------------------------------

    def pin(self, object_id: ObjectId) -> None:
        self._call(self._lib.rtpu_store_pin, object_id.hex().encode(), 1)

    def unpin(self, object_id: ObjectId) -> None:
        self._call(self._lib.rtpu_store_pin, object_id.hex().encode(), 0)

    def delete(self, object_id: ObjectId) -> None:
        self._call(self._lib.rtpu_store_delete, object_id.hex().encode())

    def stats(self) -> dict:
        import ctypes

        vals = [ctypes.c_uint64() for _ in range(5)]
        with self._lock:
            if self._h is None:
                return {"native": True, "destroyed": True}
            self._lib.rtpu_store_stats(self._h,
                                       *[ctypes.byref(v) for v in vals])
        return {"capacity": vals[1].value, "used": vals[0].value,
                "num_objects": vals[2].value,
                "num_evictions": vals[3].value,
                "num_spills": vals[4].value, "native": True,
                "num_channels": len(self._channels)}

    def destroy(self) -> None:
        with self._lock:
            if self._destroyed:
                return
            self._destroyed = True
            self._lib.rtpu_store_destroy(self._h)
            self._h = None


def make_store(node_id: NodeId, capacity_bytes: int, spill_dir: str = "",
               min_spilling_size: int = 1024 * 1024):
    """Native store when the C++ layer builds (default), else the Python
    reference implementation. RTPU_NATIVE_STORE=0 forces Python."""
    from ..native import load_store_lib

    lib = load_store_lib()
    if lib is not None and "://" not in (spill_dir or ""):
        # fsspec spill URLs route through the Python store (the C++ core
        # spills to local paths only)
        return NativePlasmaStore(lib, node_id, capacity_bytes, spill_dir,
                                 min_spilling_size)
    return PlasmaStore(node_id, capacity_bytes, spill_dir,
                       min_spilling_size)


# ---------------------------------------------------------------------------
# chunked object transfer (ref: object_manager.h:117; 5 MiB chunks per
# ray_config_def.h:348). Shared by both transfer directions: the head
# pulling from an agent and an agent pulling from the head.
# ---------------------------------------------------------------------------

TRANSFER_CHUNK = 5 * 1024 * 1024


def read_store_chunk(store: "PlasmaStore", reader: "SegmentReader",
                     object_id: ObjectId, offset: int, length: int):
    """Serve one chunk of a sealed object's bytes, or None if gone."""
    seg = store.get_segment(object_id)
    if seg is None:
        return None
    name, size = seg
    mv = reader.read(name, size)
    try:
        return bytes(mv[offset:offset + length])
    finally:
        del mv
        reader.release(name)


def pull_chunks(fetch_chunk, total: int) -> Optional[bytes]:
    """Assemble an object from sequential fetch_chunk(offset, length) calls;
    None if the source loses the object mid-transfer."""
    buf = bytearray(total)
    off = 0
    while off < total:
        n = min(TRANSFER_CHUNK, total - off)
        chunk = fetch_chunk(off, n)
        if chunk is None:
            return None
        buf[off:off + len(chunk)] = chunk
        off += len(chunk)
    return bytes(buf)


class SegmentReader:
    """Client-side zero-copy attach to sealed segments; caches attachments.

    Attaches via direct /dev/shm mmap rather than
    multiprocessing.shared_memory, so Python's global resource_tracker (one
    per cluster, inherited from the driver) never sees attach-side
    register/unregister pairs — those collide across processes on 3.12.
    The memoryview handed out references the mmap; the attachment stays open
    until release() (equivalent of the plasma client's object release)."""

    def __init__(self):
        self._attached: Dict[str, mmap.mmap] = {}
        self._lock = instrumented_lock("segment_reader")

    def read(self, shm_name: str, size: int) -> memoryview:
        with self._lock:
            mm = self._attached.get(shm_name)
            if mm is None:
                with open("/dev/shm/" + shm_name, "r+b") as f:
                    mm = mmap.mmap(f.fileno(), 0)
                self._attached[shm_name] = mm
            return memoryview(mm)[:size]

    def release(self, shm_name: str) -> None:
        with self._lock:
            mm = self._attached.pop(shm_name, None)
            if mm is not None:
                try:
                    mm.close()
                except Exception:
                    pass

    def close(self) -> None:
        with self._lock:
            for mm in self._attached.values():
                try:
                    mm.close()
                except Exception:
                    pass
            self._attached.clear()
