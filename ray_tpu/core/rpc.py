"""Bidirectional RPC channel over ``multiprocessing.connection``.

Equivalent of the reference's rpc layer (ref: src/ray/rpc/grpc_server.h,
client_call.h — callback-based client calls multiplexed on a shared channel).
Here: one duplex byte pipe (Unix socket or TCP) per peer pair. The same
protocol runs over AF_UNIX within a host and AF_INET across hosts (DCN
control plane).

Threading model (the per-peer thread-pool era ended with the round-5
219-thread flake): a process owns ONE reader hub thread multiplexing every
channel's receive side via ``multiprocessing.connection.wait``, plus one
shared elastic worker pool (threads spawn on demand and exit after an idle
timeout). Each channel contributes zero dedicated threads — its request
handlers, oneway lane, and writer are FIFO *lanes* drained on the shared
pool, so process thread count tracks concurrent load, not peer count.
"""
from __future__ import annotations

import itertools
import os
import socket
import threading
import time
import traceback
from collections import deque
from concurrent.futures import Future
from multiprocessing.connection import Client, Connection, Listener
from multiprocessing.connection import wait as _mpc_wait
from typing import Any, Callable, Dict, Optional

_REQ, _RESP, _ERR, _ONEWAY = 0, 1, 2, 3
# a coalesced frame: payload is a list of already-encoded frames. Under
# burst (task pushes, done floods, direct submits/results) the writer
# lane drains its queue into one send and the reader dispatches the
# whole batch with one wakeup — syscalls and thread hops amortize
# across the batch
_BATCH = 4
_BATCH_MAX = 64

# per-handler instrumentation (ref: the reference's per-RPC gRPC stats,
# src/ray/stats/metric_defs.cc grpc_server_req_* counters): method ->
# [calls, errors, total_seconds] backing rpc_stats(), plus a bucketed
# latency histogram per method in the shared metrics registry — in
# worker/agent processes the histogram ships to the head's /metrics
# with node/worker tags (util/metrics.py snapshot_deltas).
from ..util import metrics as _metrics

_RPC_STATS: Dict[str, list] = {}
_RPC_STATS_LOCK = threading.Lock()

# fault-injection hook (ray_tpu.chaos): None until chaos.enable()
# installs an engine — the frame paths pay one global is-None test when
# disabled, and this module never imports the chaos package
_CHAOS = None
_RPC_LATENCY = _metrics.Histogram(
    "ray_tpu_rpc_handler_seconds",
    "per-RPC-method handler latency (request and oneway frames)",
    boundaries=_metrics.FAST_BOUNDARIES, tag_keys=("method",))
_RPC_ERRORS = _metrics.Counter(
    "ray_tpu_rpc_errors_total", "per-RPC-method handler errors",
    tag_keys=("method",))


def _record_rpc(method: str, seconds: float, error: bool) -> None:
    with _RPC_STATS_LOCK:
        row = _RPC_STATS.get(method)
        if row is None:
            row = _RPC_STATS[method] = [0, 0, 0.0]
        row[0] += 1
        if error:
            row[1] += 1
        row[2] += seconds
    _RPC_LATENCY.observe(seconds, tags={"method": method})
    if error:
        _RPC_ERRORS.inc(tags={"method": method})


def rpc_stats() -> Dict[str, dict]:
    """{method: {calls, errors, total_s, avg_ms}} for every RPC method
    this process has served."""
    with _RPC_STATS_LOCK:
        return {m: {"calls": c, "errors": e, "total_s": round(t, 4),
                    "avg_ms": round(t / c * 1e3, 3) if c else 0.0}
                for m, (c, e, t) in _RPC_STATS.items()}


class ChannelClosed(Exception):
    pass


class ElasticPool:
    """Shared worker pool whose thread count tracks CONCURRENT load.

    Unlike ThreadPoolExecutor (which holds every thread it ever spawned),
    threads exit after ``idle_s`` without work, and a new thread spawns
    only when a task arrives with no idle thread to take it. A blocked
    handler therefore costs one thread for exactly as long as it blocks,
    and a process serving 50 peers sequentially runs on ~1 thread.
    The max_threads cap is a runaway backstop, far above real load."""

    def __init__(self, name: str = "rpc", idle_s: float = 8.0,
                 max_threads: int = 512):
        self._name = name
        self._idle_s = idle_s
        self._max = max_threads
        self._cv = threading.Condition()
        self._q: deque = deque()
        self._threads = 0
        self._waiting = 0
        self._seq = itertools.count()

    def submit(self, fn: Callable, *args) -> None:
        spawn = False
        with self._cv:
            self._q.append((fn, args))
            if self._waiting:
                self._cv.notify()
            # spawn whenever queue depth exceeds the waiter count, not
            # only when no waiter exists: a waiter that was ALREADY
            # notified (but hasn't reacquired the lock, so _waiting still
            # counts it) can absorb only one item — counting it for a
            # second submit would lose that wakeup, stranding the item
            # until an unrelated submit (deadlock if the running handler
            # blocks on the stranded one, e.g. a fetch whose seal
            # notification sits behind it). A spare thread idles out.
            if len(self._q) > self._waiting and self._threads < self._max:
                self._threads += 1
                spawn = True
        if spawn:
            threading.Thread(
                target=self._run, daemon=True,
                name=f"{self._name}-{next(self._seq)}").start()

    def _run(self) -> None:
        while True:
            with self._cv:
                while not self._q:
                    self._waiting += 1
                    self._cv.wait(self._idle_s)
                    self._waiting -= 1
                    if not self._q:
                        # idle timeout (or spurious wake with nothing to
                        # do): retire — submit() spawns a fresh thread
                        # when load returns
                        self._threads -= 1
                        return
                fn, args = self._q.popleft()
            try:
                fn(*args)
            except Exception:
                traceback.print_exc()

    def stats(self) -> dict:
        with self._cv:
            return {"threads": self._threads, "waiting": self._waiting,
                    "queued": len(self._q)}


_POOL_LOCK = threading.Lock()
_POOL: Optional[ElasticPool] = None


def shared_pool() -> ElasticPool:
    """The process-wide RPC worker pool (handlers, oneway lanes, writers)."""
    global _POOL
    with _POOL_LOCK:
        if _POOL is None:
            _POOL = ElasticPool("rpcw")
        return _POOL


class _Lane:
    """FIFO work lane with bounded concurrency, drained on the shared pool.

    Items keep arrival order; at most ``max_active`` drainers run at once
    (1 = strict FIFO processing — the oneway and writer lanes; N = the
    request lane's per-channel handler concurrency). No dedicated thread:
    a drainer claims a pool thread only while items exist."""

    __slots__ = ("_pool", "_fn", "_q", "_lock", "_active", "_max")

    def __init__(self, pool: ElasticPool, fn: Callable[[Any], None],
                 max_active: int = 1):
        self._pool = pool
        self._fn = fn
        self._q: deque = deque()
        self._lock = threading.Lock()
        self._active = 0
        self._max = max(1, int(max_active))

    def push(self, item) -> None:
        with self._lock:
            self._q.append(item)
            if self._active >= self._max:
                return
            self._active += 1
        self._pool.submit(self._drain)

    def _drain(self) -> None:
        while True:
            with self._lock:
                if not self._q:
                    self._active -= 1
                    return
                item = self._q.popleft()
            try:
                self._fn(item)
            except Exception:
                traceback.print_exc()

    def idle(self) -> bool:
        with self._lock:
            return not self._q and self._active == 0


class _ReaderHub:
    """One thread multiplexing every channel's receive side.

    ``multiprocessing.connection.wait`` over all registered connections;
    ready frames are decoded and dispatched to the owning channel's lanes
    (which run on the shared pool), so the hub never blocks on a handler.
    Only the hub closes a registered connection — deregistration is
    requested via flag + wakeup, which keeps the fd out of the selector
    before it goes invalid."""

    def __init__(self):
        self._lock = threading.Lock()
        self._channels: Dict[int, "RpcChannel"] = {}  # conn fileno -> ch
        self._wake_r, self._wake_w = socket.socketpair()
        self._wake_r.setblocking(False)
        self._started = False

    def _ensure_thread(self) -> None:
        if not self._started:
            self._started = True
            threading.Thread(target=self._loop, daemon=True,
                             name="rpc-hub").start()

    def register(self, ch: "RpcChannel") -> None:
        with self._lock:
            self._channels[ch._conn.fileno()] = ch
            self._ensure_thread()
        self.wake()

    def request_drop(self, ch: "RpcChannel") -> None:
        """Ask the hub to stop watching + close the channel's conn."""
        ch._drop_requested = True
        self.wake()

    def wake(self) -> None:
        try:
            self._wake_w.send(b"x")
        except Exception:
            pass

    def _loop(self) -> None:
        while True:
            with self._lock:
                dead = [ch for ch in self._channels.values()
                        if ch._drop_requested]
                for ch in dead:
                    self._channels.pop(ch._conn.fileno(), None)
                conns = {ch._conn: ch for ch in self._channels.values()}
            for ch in dead:
                try:
                    ch._conn.close()
                except Exception:
                    pass
            try:
                ready = _mpc_wait([*conns.keys(), self._wake_r])
            except Exception:
                # a conn went bad between snapshot and wait (peer died
                # mid-registration): probe each individually and drop
                # the broken ones
                for conn, ch in conns.items():
                    try:
                        conn.poll(0)
                    except Exception:
                        self._drop_broken(ch)
                continue
            for obj in ready:
                if obj is self._wake_r:
                    try:
                        while self._wake_r.recv(4096):
                            pass
                    except BlockingIOError:
                        pass
                    except Exception:
                        pass
                    continue
                ch = conns.get(obj)
                if ch is None or ch._drop_requested:
                    continue
                try:
                    data = obj.recv_bytes()
                except Exception:
                    # EOF / reset / torn down: this channel only
                    self._drop_broken(ch)
                    continue
                try:
                    ch._on_bytes(data)
                except Exception:
                    traceback.print_exc()

    def _drop_broken(self, ch: "RpcChannel") -> None:
        with self._lock:
            self._channels.pop(ch._conn.fileno(), None)
        try:
            ch._conn.close()
        except Exception:
            pass
        # teardown callbacks (worker-exit handling etc.) can be heavy:
        # run them on the pool, never on the hub thread
        shared_pool().submit(ch._teardown)


_HUB_LOCK = threading.Lock()
_HUB: Optional[_ReaderHub] = None


def reader_hub() -> _ReaderHub:
    global _HUB
    with _HUB_LOCK:
        if _HUB is None:
            _HUB = _ReaderHub()
        return _HUB


class RpcChannel:
    """A duplex message channel with request/response correlation.

    handler(method: str, payload: Any) -> Any  serves incoming requests.
    """

    def __init__(self, conn: Connection,
                 handler: Optional[Callable[[str, Any], Any]] = None,
                 num_handler_threads: Optional[int] = None,
                 name: str = "",
                 autostart: bool = True):
        if num_handler_threads is None:
            from .config import DEFAULT

            num_handler_threads = int(DEFAULT.rpc_handler_threads)
        self._conn = conn
        self._handler = handler
        self._name = name
        self._seq = itertools.count()
        self._pending: Dict[int, Future] = {}
        self._lock = threading.Lock()
        self._closed = threading.Event()
        self._started = False
        self._drop_requested = False
        self._on_close_cbs = []
        pool = shared_pool()
        # request handlers: per-channel concurrency cap (the old
        # per-channel ThreadPoolExecutor's max_workers), shared threads
        self._req_lane = _Lane(pool, self._handle_req,
                               max_active=num_handler_threads)
        # Notifications get their own single-drainer lane: they stay FIFO
        # and can never be starved by blocking request handlers (e.g. a
        # fetch waiting on an object whose seal NOTIFICATION would satisfy
        # it — the reference keeps these planes separate too: pubsub
        # long-poll vs request RPCs).
        self._ow_lane = _Lane(pool, self._handle_oneway_item, max_active=1)
        # Single-drainer writer lane owns conn.send. Senders only enqueue,
        # so a full socket buffer can never block the hub, a handler, or a
        # GC finalizer (an ObjectRef finalizer notifying remove_ref from
        # inside the reader's loop deadlocked both pipe directions before
        # this). The drain coalesces queued frames into _BATCH sends.
        self._outbox: deque = deque()
        self._out_lock = threading.Lock()
        self._out_active = False
        self._out_idle = threading.Condition(self._out_lock)
        if autostart:
            self.start()

    def start(self) -> None:
        """Begin reading. Callers that must install a handler first pass
        autostart=False — otherwise a message can race the handler install."""
        if not self._started:
            self._started = True
            reader_hub().register(self)

    # -- client side -----------------------------------------------------------

    def call(self, method: str, payload: Any = None, timeout: Optional[float] = None) -> Any:
        return self.call_async(method, payload).result(timeout)

    def call_async(self, method: str, payload: Any = None) -> Future:
        fut: Future = Future()
        msg_id = next(self._seq)
        with self._lock:
            if self._closed.is_set():
                fut.set_exception(ChannelClosed(f"channel {self._name} closed"))
                return fut
            self._pending[msg_id] = fut
        try:
            self._send((_REQ, msg_id, method, payload))
        except Exception as e:
            with self._lock:
                self._pending.pop(msg_id, None)
            if not fut.done():  # teardown may have failed it already
                fut.set_exception(ChannelClosed(str(e)))
        return fut

    def notify(self, method: str, payload: Any = None) -> None:
        """Fire-and-forget."""
        try:
            self._send((_ONEWAY, 0, method, payload))
        except Exception:
            pass

    def _send(self, msg) -> None:
        if self._closed.is_set():
            raise ChannelClosed(f"channel {self._name} closed")
        with self._out_lock:
            self._outbox.append(msg)
            if self._out_active:
                return
            self._out_active = True
        shared_pool().submit(self._write_drain)

    def _write_drain(self) -> None:
        from . import wire

        while True:
            with self._out_lock:
                if not self._outbox:
                    self._out_active = False
                    self._out_idle.notify_all()
                    return
                # drain up to a batch's worth under the lock; encoding
                # and the send syscall happen outside it
                msgs = [self._outbox.popleft()
                        for _ in range(min(len(self._outbox), _BATCH_MAX))]
            if _CHAOS is not None:
                # seeded drop/delay/duplicate/reorder of outbound frames
                # (oneway only for drop/dup — see ray_tpu.chaos docs)
                msgs = _CHAOS.rpc_send(msgs)
                if not msgs:
                    continue
            frames = []
            for msg in msgs:
                try:
                    # typed frames, never pickle: see wire.py (the
                    # reference's control plane is protobuf/gRPC; pickle
                    # framing here was an RCE amplifier behind one token)
                    frames.append(wire.encode(msg))
                except wire.WireEncodeError:
                    traceback.print_exc()
                    self._fail_encode(msg)
                except Exception:
                    self._teardown()
                    with self._out_lock:
                        self._out_active = False
                        self._out_idle.notify_all()
                    return
            if not frames:
                continue
            try:
                if len(frames) == 1:
                    self._conn.send_bytes(frames[0])
                else:
                    self._conn.send_bytes(
                        wire.encode((_BATCH, 0, None, frames)))
            except Exception:
                self._teardown()
                with self._out_lock:
                    self._out_active = False
                    self._out_idle.notify_all()
                return

    def _flush_writer(self, timeout: float) -> None:
        deadline = time.monotonic() + timeout
        with self._out_lock:
            while self._outbox or self._out_active:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return
                self._out_idle.wait(remaining)

    def _fail_encode(self, msg) -> None:
        """One bad payload must not kill the channel — but it must not
        strand its correlated future either: fail a _REQ's future
        locally; answer a _RESP's caller with an _ERR."""
        from . import wire

        kind, msg_id = msg[0], msg[1]
        if kind == _REQ:
            with self._lock:
                fut = self._pending.pop(msg_id, None)
            if fut is not None and not fut.done():
                fut.set_exception(wire.WireEncodeError(
                    f"payload for {msg[2]!r} not wire-encodable"))
        elif kind == _RESP:
            try:
                self._conn.send_bytes(wire.encode(
                    (_ERR, msg_id, "WireEncodeError: unencodable response",
                     "")))
            except Exception:
                pass

    # -- server side -----------------------------------------------------------

    def set_handler(self, handler: Callable[[str, Any], Any]) -> None:
        self._handler = handler

    def _on_bytes(self, data: bytes) -> None:
        """Hub delivery of one raw frame: decode + route to lanes. Runs on
        the hub thread — must never block on a handler."""
        from . import wire

        try:
            msg = wire.decode(data)
            kind, msg_id, a, b = msg
            if not isinstance(kind, int) or not isinstance(msg_id, int):
                raise wire.WireDecodeError("bad frame header")
        except (wire.WireDecodeError, ValueError, TypeError):
            # malformed/malicious frame: it was never evaluated — drop it
            # and keep serving (a pickle-framing channel would have
            # executed it on recv)
            traceback.print_exc()
            return
        if kind == _BATCH:
            self._dispatch_batch(b)
        else:
            self._dispatch_frame(kind, msg_id, a, b)

    def _dispatch_batch(self, frames) -> None:
        from . import wire

        if not isinstance(frames, (list, tuple)):
            return  # malformed batch body: drop
        for data in frames:
            try:
                kind, msg_id, a, b = wire.decode(data)
                if not isinstance(kind, int) or not isinstance(msg_id, int):
                    raise wire.WireDecodeError("bad frame header")
            except (wire.WireDecodeError, ValueError, TypeError):
                traceback.print_exc()
                continue
            if kind == _BATCH:
                continue  # no nesting
            self._dispatch_frame(kind, msg_id, a, b)

    def _dispatch_frame(self, kind: int, msg_id: int, a, b) -> None:
        if kind == _RESP:
            with self._lock:
                fut = self._pending.pop(msg_id, None)
            if fut is not None:
                fut.set_result(b)
        elif kind == _ERR:
            with self._lock:
                fut = self._pending.pop(msg_id, None)
            if fut is not None:
                fut.set_exception(_RemoteCallError(a, b))
        elif kind == _REQ:
            self._req_lane.push((msg_id, a, b))
        elif kind == _ONEWAY:
            if _CHAOS is not None and _CHAOS.recv_drop(a):
                return  # injected receiver-side loss
            self._ow_lane.push((a, b))

    def _handle_req(self, item) -> None:
        msg_id, method, payload = item
        t0 = time.perf_counter()
        ok = False
        try:
            result = self._handler(method, payload)
            self._send((_RESP, msg_id, None, result))
            ok = True  # only after the reply went out: a failed _RESP
            # send IS a client-visible error and must count as one
        except Exception as e:
            try:
                self._send((_ERR, msg_id, f"{type(e).__name__}: {e}",
                            traceback.format_exc()))
            except Exception:
                pass
        finally:
            _record_rpc(method, time.perf_counter() - t0, not ok)

    def _handle_oneway_item(self, item) -> None:
        method, payload = item
        t0 = time.perf_counter()
        ok = False
        try:
            self._handler(method, payload)
            ok = True
        except Exception:
            traceback.print_exc()
        finally:
            _record_rpc(method, time.perf_counter() - t0, not ok)

    # -- lifecycle -------------------------------------------------------------

    def on_close(self, cb: Callable[[], None]) -> None:
        with self._lock:
            if not self._closed.is_set():
                self._on_close_cbs.append(cb)
                return
        # teardown already ran: fire immediately so late registrants (e.g.
        # a node handle built while the peer was dying) still observe the
        # death
        try:
            cb()
        except Exception:
            traceback.print_exc()

    def _teardown(self) -> None:
        with self._lock:
            if self._closed.is_set():
                return
            self._closed.set()
            pending = list(self._pending.values())
            self._pending.clear()
        for fut in pending:
            if not fut.done():
                fut.set_exception(ChannelClosed(f"channel {self._name} closed"))
        for cb in self._on_close_cbs:
            try:
                cb()
            except Exception:
                traceback.print_exc()

    def close(self) -> None:
        self._teardown()
        # give the writer lane a moment to flush already-queued messages
        # (e.g. a final "shutdown" notify) before the connection drops
        self._flush_writer(2.0)
        if self._started:
            # a registered conn is only closed by the hub, so the fd never
            # goes invalid inside the selector
            reader_hub().request_drop(self)
        else:
            try:
                self._conn.close()
            except Exception:
                pass

    @property
    def closed(self) -> bool:
        return self._closed.is_set()


class _RemoteCallError(Exception):
    def __init__(self, summary: str, remote_tb: str):
        super().__init__(f"{summary}\n--- remote traceback ---\n{remote_tb}")
        self.summary = summary
        self.remote_tb = remote_tb

    def __reduce__(self):
        # default Exception reduction replays args=(message,) into the
        # 2-arg __init__ and fails on unpickle — these DO cross process
        # boundaries when a task result carries one
        return (_RemoteCallError, (self.summary, self.remote_tb))


_CLUSTER_TOKEN: Optional[bytes] = None


def cluster_token() -> bytes:
    """Per-cluster RPC auth token.

    multiprocessing.connection unpickles peer payloads, so a guessable
    authkey means anyone who can reach the head port gets code execution
    on every node (the reference's cross-host plane is gRPC/protobuf and
    has no such amplification). The token is generated fresh per head
    process, inherited by worker/agent subprocesses through the
    RTPU_AUTHKEY env var, and handed to remote machines via the join
    command `ray_tpu start --head` prints. The port must still only be
    exposed on a trusted network — the token authenticates, it does not
    encrypt."""
    global _CLUSTER_TOKEN
    if _CLUSTER_TOKEN is None:
        env = os.environ.get("RTPU_AUTHKEY", "")
        if env:
            _CLUSTER_TOKEN = bytes.fromhex(env)
        else:
            import secrets

            _CLUSTER_TOKEN = secrets.token_bytes(32)
            # exported so child processes (workers, agents started from
            # this process) authenticate without the key appearing in argv
            os.environ["RTPU_AUTHKEY"] = _CLUSTER_TOKEN.hex()
    return _CLUSTER_TOKEN


class RpcServer:
    """Accepts channel connections on a Unix or TCP socket."""

    def __init__(self, address, handler_factory: Callable[[RpcChannel], Callable],
                 family: Optional[str] = None, authkey: Optional[bytes] = None,
                 num_handler_threads: int = 16):
        # backlog: the multiprocessing default of 1 refuses concurrent
        # connects (peer direct-call channels + multi-driver bursts all
        # land at once); a refused connect reads as "unreachable" and
        # would push callers onto the routed path
        self._listener = Listener(address, family=family, backlog=64,
                                  authkey=authkey or cluster_token())
        self._handler_factory = handler_factory
        self._num_handler_threads = num_handler_threads
        self._channels = []
        self._stopped = threading.Event()
        self._accept_thread = threading.Thread(target=self._accept_loop, daemon=True,
                                               name="rpc-accept")
        self._accept_thread.start()

    @property
    def address(self):
        return self._listener.address

    def _accept_loop(self) -> None:
        while not self._stopped.is_set():
            try:
                conn = self._listener.accept()
            except Exception:
                # A peer dying mid-handshake raises here; keep accepting —
                # only a closed listener ends the loop.
                if self._stopped.is_set():
                    break
                try:
                    # closed listener raises immediately again; back off a hair
                    import time as _t

                    _t.sleep(0.01)
                    if self._listener._listener is None:  # type: ignore[attr-defined]
                        break
                except Exception:
                    break
                continue
            chan = RpcChannel(conn, name="srv",
                              num_handler_threads=self._num_handler_threads,
                              autostart=False)
            chan.set_handler(self._handler_factory(chan))
            chan.start()
            self._channels.append(chan)

    def close(self) -> None:
        self._stopped.set()
        try:
            self._listener.close()
        except Exception:
            pass
        for ch in self._channels:
            ch.close()


def connect(address, authkey: Optional[bytes] = None,
            handler: Optional[Callable[[str, Any], Any]] = None,
            name: str = "",
            num_handler_threads: Optional[int] = None) -> RpcChannel:
    conn = Client(address, authkey=authkey or cluster_token())
    return RpcChannel(conn, handler=handler, name=name,
                      num_handler_threads=num_handler_threads)
