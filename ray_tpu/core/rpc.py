"""Bidirectional RPC channel over ``multiprocessing.connection``.

Equivalent of the reference's rpc layer (ref: src/ray/rpc/grpc_server.h,
client_call.h — callback-based client calls multiplexed on a shared channel).
Here: one duplex byte pipe (Unix socket or TCP) per peer pair; a reader thread
demultiplexes responses (resolving futures) and dispatches incoming requests
to a handler pool, so nested calls never deadlock. The same protocol runs over
AF_UNIX within a host and AF_INET across hosts (DCN control plane).
"""
from __future__ import annotations

import itertools
import os
import queue as queue_mod
import threading
import time
import traceback
from concurrent.futures import Future, ThreadPoolExecutor
from multiprocessing.connection import Client, Connection, Listener
from typing import Any, Callable, Dict, Optional

_REQ, _RESP, _ERR, _ONEWAY = 0, 1, 2, 3
# a coalesced frame: payload is a list of already-encoded frames. Under
# burst (task pushes, done floods) the writer drains its queue into one
# send and the reader dispatches the whole batch with one wakeup —
# syscalls and thread hops amortize across the batch
_BATCH = 4
_BATCH_MAX = 64
_CLOSE = object()  # writer-thread sentinel

# per-handler instrumentation (ref: the reference's per-RPC gRPC stats,
# src/ray/stats/metric_defs.cc grpc_server_req_* counters): method ->
# [calls, errors, total_seconds] backing rpc_stats(), plus a bucketed
# latency histogram per method in the shared metrics registry — in
# worker/agent processes the histogram ships to the head's /metrics
# with node/worker tags (util/metrics.py snapshot_deltas).
from ..util import metrics as _metrics

_RPC_STATS: Dict[str, list] = {}
_RPC_STATS_LOCK = threading.Lock()
_RPC_LATENCY = _metrics.Histogram(
    "ray_tpu_rpc_handler_seconds",
    "per-RPC-method handler latency (request and oneway frames)",
    boundaries=_metrics.FAST_BOUNDARIES, tag_keys=("method",))
_RPC_ERRORS = _metrics.Counter(
    "ray_tpu_rpc_errors_total", "per-RPC-method handler errors",
    tag_keys=("method",))


def _record_rpc(method: str, seconds: float, error: bool) -> None:
    with _RPC_STATS_LOCK:
        row = _RPC_STATS.get(method)
        if row is None:
            row = _RPC_STATS[method] = [0, 0, 0.0]
        row[0] += 1
        if error:
            row[1] += 1
        row[2] += seconds
    _RPC_LATENCY.observe(seconds, tags={"method": method})
    if error:
        _RPC_ERRORS.inc(tags={"method": method})


def rpc_stats() -> Dict[str, dict]:
    """{method: {calls, errors, total_s, avg_ms}} for every RPC method
    this process has served."""
    with _RPC_STATS_LOCK:
        return {m: {"calls": c, "errors": e, "total_s": round(t, 4),
                    "avg_ms": round(t / c * 1e3, 3) if c else 0.0}
                for m, (c, e, t) in _RPC_STATS.items()}


class ChannelClosed(Exception):
    pass


class RpcChannel:
    """A duplex message channel with request/response correlation.

    handler(method: str, payload: Any) -> Any  serves incoming requests.
    """

    def __init__(self, conn: Connection,
                 handler: Optional[Callable[[str, Any], Any]] = None,
                 num_handler_threads: Optional[int] = None,
                 name: str = "",
                 autostart: bool = True):
        if num_handler_threads is None:
            from .config import DEFAULT

            num_handler_threads = int(DEFAULT.rpc_handler_threads)
        self._conn = conn
        self._handler = handler
        self._name = name
        self._seq = itertools.count()
        self._pending: Dict[int, Future] = {}
        self._lock = threading.Lock()
        self._closed = threading.Event()
        self._started = False
        self._on_close_cbs = []
        self._pool = ThreadPoolExecutor(max_workers=num_handler_threads,
                                        thread_name_prefix=f"rpc-{name}")
        # Notifications get their own single-thread lane: they stay FIFO
        # and can never be starved by blocking request handlers (e.g. a
        # fetch waiting on an object whose seal NOTIFICATION would satisfy
        # it — the reference keeps these planes separate too: pubsub
        # long-poll vs request RPCs).
        self._oneway_pool = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix=f"rpc-ow-{name}")
        self._reader = threading.Thread(target=self._read_loop, daemon=True,
                                        name=f"rpc-reader-{name}")
        # Single writer thread owns conn.send. Senders only enqueue, so a
        # full socket buffer can never block the reader thread, a handler,
        # or a GC finalizer (an ObjectRef finalizer notifying remove_ref
        # from inside the reader's read loop deadlocked both pipe
        # directions before this).
        self._out_q: queue_mod.SimpleQueue = queue_mod.SimpleQueue()
        self._writer = threading.Thread(target=self._write_loop, daemon=True,
                                        name=f"rpc-writer-{name}")
        if autostart:
            self.start()

    def start(self) -> None:
        """Begin reading. Callers that must install a handler first pass
        autostart=False — otherwise a message can race the handler install."""
        if not self._started:
            self._started = True
            self._writer.start()
            self._reader.start()

    # -- client side -----------------------------------------------------------

    def call(self, method: str, payload: Any = None, timeout: Optional[float] = None) -> Any:
        return self.call_async(method, payload).result(timeout)

    def call_async(self, method: str, payload: Any = None) -> Future:
        fut: Future = Future()
        msg_id = next(self._seq)
        with self._lock:
            if self._closed.is_set():
                fut.set_exception(ChannelClosed(f"channel {self._name} closed"))
                return fut
            self._pending[msg_id] = fut
        try:
            self._send((_REQ, msg_id, method, payload))
        except Exception as e:
            with self._lock:
                self._pending.pop(msg_id, None)
            if not fut.done():  # teardown may have failed it already
                fut.set_exception(ChannelClosed(str(e)))
        return fut

    def notify(self, method: str, payload: Any = None) -> None:
        """Fire-and-forget."""
        try:
            self._send((_ONEWAY, 0, method, payload))
        except Exception:
            pass

    def _send(self, msg) -> None:
        if self._closed.is_set():
            raise ChannelClosed(f"channel {self._name} closed")
        self._out_q.put(msg)

    def _write_loop(self) -> None:
        from . import wire

        while True:
            msg = self._out_q.get()
            if msg is _CLOSE:
                return
            try:
                # typed frames, never pickle: see wire.py (the reference's
                # control plane is protobuf/gRPC; pickle framing here was
                # an RCE amplifier behind one shared token)
                frame = wire.encode(msg)
                extra = []
                close_after = False
                while len(extra) < _BATCH_MAX - 1:
                    try:
                        nxt = self._out_q.get_nowait()
                    except queue_mod.Empty:
                        break
                    if nxt is _CLOSE:
                        close_after = True
                        break
                    try:
                        extra.append(wire.encode(nxt))
                    except wire.WireEncodeError:
                        traceback.print_exc()
                        self._fail_encode(nxt)
                if extra:
                    self._conn.send_bytes(
                        wire.encode((_BATCH, 0, None, [frame, *extra])))
                else:
                    self._conn.send_bytes(frame)
                if close_after:
                    return
            except wire.WireEncodeError:
                traceback.print_exc()
                self._fail_encode(msg)
                continue
            except Exception:
                self._teardown()
                return

    def _fail_encode(self, msg) -> None:
        """One bad payload must not kill the channel — but it must not
        strand its correlated future either: fail a _REQ's future
        locally; answer a _RESP's caller with an _ERR."""
        from . import wire

        kind, msg_id = msg[0], msg[1]
        if kind == _REQ:
            with self._lock:
                fut = self._pending.pop(msg_id, None)
            if fut is not None and not fut.done():
                fut.set_exception(wire.WireEncodeError(
                    f"payload for {msg[2]!r} not wire-encodable"))
        elif kind == _RESP:
            try:
                self._conn.send_bytes(wire.encode(
                    (_ERR, msg_id, "WireEncodeError: unencodable response",
                     "")))
            except Exception:
                pass

    # -- server side -----------------------------------------------------------

    def set_handler(self, handler: Callable[[str, Any], Any]) -> None:
        self._handler = handler

    def _read_loop(self) -> None:
        from . import wire

        try:
            while not self._closed.is_set():
                try:
                    data = self._conn.recv_bytes()
                except (EOFError, OSError, BrokenPipeError):
                    break
                except TypeError:
                    break  # connection torn down mid-recv at interpreter exit
                try:
                    msg = wire.decode(data)
                    kind, msg_id, a, b = msg
                    if not isinstance(kind, int) or not isinstance(msg_id, int):
                        raise wire.WireDecodeError("bad frame header")
                except (wire.WireDecodeError, ValueError, TypeError):
                    # malformed/malicious frame: it was never evaluated —
                    # drop it and keep serving (a pickle-framing channel
                    # would have executed it on recv)
                    traceback.print_exc()
                    continue
                if kind == _BATCH:
                    if not self._dispatch_batch(b):
                        break
                elif not self._dispatch_frame(kind, msg_id, a, b):
                    break
        finally:
            self._teardown()

    def _dispatch_batch(self, frames) -> bool:
        """Decode and dispatch a writer-coalesced batch. Consecutive
        oneways run as ONE pool item (they are FIFO on the oneway lane
        anyway) so a 64-frame done-flood costs one thread hop."""
        from . import wire

        if not isinstance(frames, (list, tuple)):
            return True  # malformed batch body: drop
        oneway_run: list = []

        def flush_oneways() -> bool:
            if not oneway_run:
                return True
            run = list(oneway_run)
            oneway_run.clear()
            try:
                self._oneway_pool.submit(self._handle_oneway_many, run)
            except RuntimeError:
                return False
            return True

        for data in frames:
            try:
                kind, msg_id, a, b = wire.decode(data)
                if not isinstance(kind, int) or not isinstance(msg_id, int):
                    raise wire.WireDecodeError("bad frame header")
            except (wire.WireDecodeError, ValueError, TypeError):
                traceback.print_exc()
                continue
            if kind == _ONEWAY:
                oneway_run.append((a, b))
                continue
            if not flush_oneways():
                return False
            if kind == _BATCH:
                continue  # no nesting
            if not self._dispatch_frame(kind, msg_id, a, b):
                return False
        return flush_oneways()

    def _dispatch_frame(self, kind: int, msg_id: int, a, b) -> bool:
        """Route one decoded frame; False = channel is closing."""
        if kind == _RESP:
            with self._lock:
                fut = self._pending.pop(msg_id, None)
            if fut is not None:
                fut.set_result(b)
        elif kind == _ERR:
            with self._lock:
                fut = self._pending.pop(msg_id, None)
            if fut is not None:
                fut.set_exception(_RemoteCallError(a, b))
        elif kind == _REQ:
            try:
                self._pool.submit(self._handle, msg_id, a, b)
            except RuntimeError:
                return False  # pool shut down: channel is closing
        elif kind == _ONEWAY:
            try:
                self._oneway_pool.submit(self._handle_oneway, a, b)
            except RuntimeError:
                return False
        return True

    def _handle_oneway_many(self, items) -> None:
        for a, b in items:
            self._handle_oneway(a, b)

    def _handle(self, msg_id: int, method: str, payload: Any) -> None:
        t0 = time.perf_counter()
        ok = False
        try:
            result = self._handler(method, payload)
            self._send((_RESP, msg_id, None, result))
            ok = True  # only after the reply went out: a failed _RESP
            # send IS a client-visible error and must count as one
        except Exception as e:
            try:
                self._send((_ERR, msg_id, f"{type(e).__name__}: {e}", traceback.format_exc()))
            except Exception:
                pass
        finally:
            _record_rpc(method, time.perf_counter() - t0, not ok)

    def _handle_oneway(self, method: str, payload: Any) -> None:
        t0 = time.perf_counter()
        ok = False
        try:
            self._handler(method, payload)
            ok = True
        except Exception:
            traceback.print_exc()
        finally:
            _record_rpc(method, time.perf_counter() - t0, not ok)

    # -- lifecycle -------------------------------------------------------------

    def on_close(self, cb: Callable[[], None]) -> None:
        with self._lock:
            if not self._closed.is_set():
                self._on_close_cbs.append(cb)
                return
        # teardown already ran: fire immediately so late registrants (e.g.
        # a node handle built while the peer was dying) still observe the
        # death
        try:
            cb()
        except Exception:
            traceback.print_exc()

    def _teardown(self) -> None:
        with self._lock:
            if self._closed.is_set():
                return
            self._closed.set()
            pending = list(self._pending.values())
            self._pending.clear()
        self._out_q.put(_CLOSE)  # let the writer drain queued sends, then exit
        for fut in pending:
            if not fut.done():
                fut.set_exception(ChannelClosed(f"channel {self._name} closed"))
        for cb in self._on_close_cbs:
            try:
                cb()
            except Exception:
                traceback.print_exc()
        self._pool.shutdown(wait=False)
        self._oneway_pool.shutdown(wait=False)

    def close(self) -> None:
        self._teardown()
        # give the writer a moment to flush already-queued messages (e.g. a
        # final "shutdown" notify) before the connection drops
        if self._started and threading.current_thread() is not self._writer:
            self._writer.join(timeout=2.0)
        try:
            self._conn.close()
        except Exception:
            pass

    @property
    def closed(self) -> bool:
        return self._closed.is_set()


class _RemoteCallError(Exception):
    def __init__(self, summary: str, remote_tb: str):
        super().__init__(f"{summary}\n--- remote traceback ---\n{remote_tb}")
        self.summary = summary
        self.remote_tb = remote_tb

    def __reduce__(self):
        # default Exception reduction replays args=(message,) into the
        # 2-arg __init__ and fails on unpickle — these DO cross process
        # boundaries when a task result carries one
        return (_RemoteCallError, (self.summary, self.remote_tb))


_CLUSTER_TOKEN: Optional[bytes] = None


def cluster_token() -> bytes:
    """Per-cluster RPC auth token.

    multiprocessing.connection unpickles peer payloads, so a guessable
    authkey means anyone who can reach the head port gets code execution
    on every node (the reference's cross-host plane is gRPC/protobuf and
    has no such amplification). The token is generated fresh per head
    process, inherited by worker/agent subprocesses through the
    RTPU_AUTHKEY env var, and handed to remote machines via the join
    command `ray_tpu start --head` prints. The port must still only be
    exposed on a trusted network — the token authenticates, it does not
    encrypt."""
    global _CLUSTER_TOKEN
    if _CLUSTER_TOKEN is None:
        env = os.environ.get("RTPU_AUTHKEY", "")
        if env:
            _CLUSTER_TOKEN = bytes.fromhex(env)
        else:
            import secrets

            _CLUSTER_TOKEN = secrets.token_bytes(32)
            # exported so child processes (workers, agents started from
            # this process) authenticate without the key appearing in argv
            os.environ["RTPU_AUTHKEY"] = _CLUSTER_TOKEN.hex()
    return _CLUSTER_TOKEN


class RpcServer:
    """Accepts channel connections on a Unix or TCP socket."""

    def __init__(self, address, handler_factory: Callable[[RpcChannel], Callable],
                 family: Optional[str] = None, authkey: Optional[bytes] = None,
                 num_handler_threads: int = 16):
        self._listener = Listener(address, family=family,
                                  authkey=authkey or cluster_token())
        self._handler_factory = handler_factory
        self._num_handler_threads = num_handler_threads
        self._channels = []
        self._stopped = threading.Event()
        self._accept_thread = threading.Thread(target=self._accept_loop, daemon=True,
                                               name="rpc-accept")
        self._accept_thread.start()

    @property
    def address(self):
        return self._listener.address

    def _accept_loop(self) -> None:
        while not self._stopped.is_set():
            try:
                conn = self._listener.accept()
            except Exception:
                # A peer dying mid-handshake raises here; keep accepting —
                # only a closed listener ends the loop.
                if self._stopped.is_set():
                    break
                try:
                    # closed listener raises immediately again; back off a hair
                    import time as _t

                    _t.sleep(0.01)
                    if self._listener._listener is None:  # type: ignore[attr-defined]
                        break
                except Exception:
                    break
                continue
            chan = RpcChannel(conn, name="srv",
                              num_handler_threads=self._num_handler_threads,
                              autostart=False)
            chan.set_handler(self._handler_factory(chan))
            chan.start()
            self._channels.append(chan)

    def close(self) -> None:
        self._stopped.set()
        try:
            self._listener.close()
        except Exception:
            pass
        for ch in self._channels:
            ch.close()


def connect(address, authkey: Optional[bytes] = None,
            handler: Optional[Callable[[str, Any], Any]] = None,
            name: str = "",
            num_handler_threads: Optional[int] = None) -> RpcChannel:
    conn = Client(address, authkey=authkey or cluster_token())
    return RpcChannel(conn, handler=handler, name=name,
                      num_handler_threads=num_handler_threads)
