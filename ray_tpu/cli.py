"""Command-line interface (ref: python/ray/scripts/scripts.py:71
`ray start/stop/status`).

`ray_tpu start --head --port P`    — standalone head: hosts GCS + the head
                                     node and listens for joining agents.
`ray_tpu start --address H:P`      — node agent joining a head (the remote
                                     half of the multi-host runtime).
`ray_tpu status --address H:P`     — print cluster nodes/resources.

Usage: python -m ray_tpu <command> [options]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time


def _cmd_start(args) -> int:
    if args.head:
        from .core import runtime as runtime_mod
        from .core.runtime import DriverRuntime

        resources = {"CPU": args.num_cpus, **json.loads(args.resources)}
        rt = DriverRuntime(resources=resources)
        runtime_mod.set_runtime(rt)
        from .core.rpc import cluster_token

        addr = rt.enable_remote_nodes(host=args.host, port=args.port)
        print(f"ray_tpu head listening on {addr[0]}:{addr[1]}")
        print(f"Join more nodes with:\n  python -m ray_tpu start "
              f"--address {addr[0]}:{addr[1]} "
              f"--authkey {cluster_token().hex()}")
        try:
            while True:
                time.sleep(1.0)
        except KeyboardInterrupt:
            rt.shutdown()
        return 0
    if not args.address:
        print("start needs --head or --address HOST:PORT", file=sys.stderr)
        return 2
    from .core.node_agent import main as agent_main

    agent_args = ["--address", args.address,
                  "--num-cpus", str(args.num_cpus),
                  "--resources", args.resources,
                  "--labels", args.labels]
    if args.authkey:
        agent_args += ["--authkey", args.authkey]
    return agent_main(agent_args)


def _no_runtime_help() -> int:
    print("No ray_tpu runtime in this process. `list`/`timeline` read the "
          "in-process head state — call them from the driver (e.g. "
          "ray_tpu.cli.main(['list', 'summary'])) or use the state API "
          "(ray_tpu.util.state) directly.", file=sys.stderr)
    return 1


def _cmd_list(args) -> int:
    from .core import runtime as runtime_mod
    from .util import state

    if runtime_mod.maybe_runtime() is None:
        return _no_runtime_help()
    if args.what == "latency":
        _print_latency_table(state.latency_summary())
        return 0
    fn = {"nodes": state.list_nodes, "actors": state.list_actors,
          "tasks": state.list_tasks, "objects": state.list_objects,
          "pgs": state.list_placement_groups,
          "summary": state.summary}[args.what]
    rows = fn()
    print(json.dumps(rows, indent=2, default=str))
    return 0


def _print_latency_table(summary: dict) -> None:
    """Aligned p50/p95/p99 table per latency histogram (cluster-wide:
    worker/agent-shipped series are already merged in)."""
    cols = ("histogram", "count", "mean_ms", "p50_ms", "p95_ms", "p99_ms")

    def ms(v):
        return "-" if v is None else f"{v * 1e3:.2f}"

    rows = [(name, str(s["count"]), ms(s["mean"]), ms(s["p50"]),
             ms(s["p95"]), ms(s["p99"]))
            for name, s in sorted(summary.items(),
                                  key=lambda kv: -kv[1]["count"])]
    widths = [max(len(c), *(len(r[i]) for r in rows)) if rows else len(c)
              for i, c in enumerate(cols)]
    print("  ".join(c.ljust(w) for c, w in zip(cols, widths)))
    for r in rows:
        print("  ".join(v.ljust(w) for v, w in zip(r, widths)))
    if not rows:
        print("(no latency observations yet)")


def _cmd_timeline(args) -> int:
    from .core import runtime as runtime_mod
    from .util.state import timeline

    if runtime_mod.maybe_runtime() is None:
        return _no_runtime_help()
    events = timeline(output_path=args.output)
    print(f"wrote {len(events)} trace events to {args.output} "
          f"(open in chrome://tracing or https://ui.perfetto.dev)")
    return 0


def _fmt_log_record(r: dict) -> str:
    import datetime

    t = datetime.datetime.fromtimestamp(r.get("ts") or 0).strftime(
        "%H:%M:%S.%f")[:-3]
    who = f"{(r.get('worker_id') or '')[:8]} pid={r.get('pid')}"
    attrib = ""
    if r.get("task_id"):
        attrib += f" task={r['task_id'][:8]}"
    if r.get("actor_id"):
        attrib += f" actor={r['actor_id'][:8]}"
    stream = r.get("stream", "")
    mark = {"stderr": " err", "log": f" {r.get('level', 'INFO')}"}.get(
        stream, "")
    return (f"[{t} {(r.get('node_id') or '')[:8]} {who}{attrib}{mark}] "
            f"{r.get('line', '')}")


def _logs_backend(args):
    """-> query(dict)->{"records","cursor"} against the in-process head
    or, with --address, a running head over TCP (plus a closer)."""
    if getattr(args, "address", ""):
        ch = _head_channel(args)
        return (lambda q: ch.call("logs_query", q, timeout=None)), ch.close
    from .core import runtime as runtime_mod

    if runtime_mod.maybe_runtime() is None:
        return None, None
    from .util import state

    return (lambda q: state.logs(**q)), (lambda: None)


def _cmd_logs(args) -> int:
    """`ray_tpu logs [--follow] [--task|--actor|--worker|--node|--errors]`
    — query/stream the head's attributed log store (ref: `ray logs`)."""
    query, closer = _logs_backend(args)
    if query is None:
        return _no_runtime_help()
    base = {"job_id": args.job or None, "task_id": args.task or None,
            "actor_id": args.actor or None,
            "worker_id": args.worker or None,
            "node_id": args.node or None,
            "stream": args.stream or None,
            "errors_only": bool(args.errors)}
    try:
        res = query({**base, "limit": args.limit})
        for r in res["records"]:
            print(_fmt_log_record(r))
        if not args.follow:
            if not res["records"]:
                print("(no matching log records)", file=sys.stderr)
            return 0
        cursor = res["cursor"]
        while True:
            res = query({**base, "since": cursor, "limit": 1000,
                         "follow_timeout": 10.0})
            cursor = res["cursor"]
            for r in res["records"]:
                print(_fmt_log_record(r))
    except KeyboardInterrupt:
        return 0
    finally:
        closer()


def _trace_backend(args):
    """-> call(method, payload) against the in-process head or, with
    --address, a running head over TCP (plus a closer)."""
    if getattr(args, "address", ""):
        ch = _head_channel(args)
        return (lambda m, q: ch.call(m, q, timeout=None)), ch.close
    from .core import runtime as runtime_mod

    rt = runtime_mod.maybe_runtime()
    if rt is None or not hasattr(rt, "gcs"):
        return None, None

    def call(method, payload):
        if method == "traces_query":
            return rt.gcs.traces.query(**(payload or {}))
        if method == "trace_get":
            return rt.gcs.traces.get(payload)
        from .util.state import _span_trace_events

        tr = rt.gcs.traces.get(payload)
        return (_span_trace_events(list(tr.get("spans_detail", ())))
                if tr else None)

    return call, (lambda: None)


# span attributes worth a column in the tree (everything else renders
# only under --verbose); order = display order
_TRACE_ATTRS = ("deployment", "replica", "engine", "method", "session",
                "request_id", "status", "reason", "hop", "tokens",
                "cached_tokens", "cache_hit_tokens", "cache_miss_tokens",
                "prompt", "generated", "preemptions", "error")


def _render_trace_tree(detail: dict, verbose: bool = False) -> str:
    """Span tree with per-hop wall/gap breakdown: each line shows the
    span's offset from trace start, its wall duration, and (when > 1 ms)
    the GAP since its parent's start / previous sibling's end — where
    the request sat in a queue or on the wire between hops."""
    spans = list(detail.get("spans_detail", ()))
    t0 = min((float(s.get("time") or 0.0) for s in spans),
             default=float(detail.get("start") or 0.0))
    ids = {s.get("span_id") for s in spans}
    kids: dict = {}
    roots = []
    for s in spans:
        p = s.get("parent_span_id")
        if p and p in ids:
            kids.setdefault(p, []).append(s)
        else:
            roots.append(s)
    lines = [
        f"trace {detail.get('trace_id', '')} — "
        f"{float(detail.get('duration_s') or 0.0) * 1e3:.1f}ms — "
        f"{len(spans)} span(s), {detail.get('procs', 1)} process(es)"
        + (f" — kept={detail['keep_reason']}"
           if detail.get("keep_reason") else "")
        + ("" if detail.get("done") else " — OPEN")]

    def fmt(s, depth, prev_end):
        b = float(s.get("time") or 0.0)
        e = float(s.get("end_time") or b)
        attrs = dict(s.get("attributes") or {})
        gap = b - (prev_end if prev_end is not None else b)
        cols = [f"{'  ' * depth}{s.get('name', 'span'):<{28 - 2 * depth}s}",
                f"+{(b - t0) * 1e3:8.1f}ms", f"{(e - b) * 1e3:9.1f}ms",
                f"gap={gap * 1e3:.1f}ms" if gap > 1e-3 else " " * 9]
        shown = [(k, attrs[k]) for k in _TRACE_ATTRS
                 if attrs.get(k) not in (None, "", 0, False)]
        if verbose:
            shown += sorted((k, v) for k, v in attrs.items()
                            if k not in _TRACE_ATTRS)
        cols.append(" ".join(f"{k}={v}" for k, v in shown))
        lines.append("  " + " ".join(cols).rstrip())
        prev = None  # first child gaps against THIS span's start
        for c in sorted(kids.get(s.get("span_id"), ()),
                        key=lambda x: float(x.get("time") or 0.0)):
            fmt(c, depth + 1, prev if prev is not None else b)
            prev = float(c.get("end_time") or c.get("time") or 0.0)

    for r in sorted(roots, key=lambda x: float(x.get("time") or 0.0)):
        fmt(r, 0, None)
    return "\n".join(lines)


def _fmt_trace_summary(t: dict) -> str:
    dur = float(t.get("duration_s") or 0.0)
    return (f"{t.get('trace_id', ''):32s}  {dur * 1e3:9.1f}ms  "
            f"spans={t.get('spans', 0):<4d} procs={t.get('procs', 1):<2d} "
            f"kept={t.get('keep_reason') or '-':8s} "
            f"{t.get('deployment') or '-':16s} "
            f"req={t.get('request_id') or '-'}")


def _cmd_trace(args) -> int:
    """`ray_tpu trace <id> | --request R | --session S | --slowest N`
    — render one stored trace's span tree (per-hop wall/gap breakdown)
    or list tail-kept trace summaries; ids may be unique hex prefixes
    (e.g. straight off a /metrics exemplar)."""
    call, closer = _trace_backend(args)
    if call is None:
        return _no_runtime_help()
    try:
        if args.trace_id:
            if args.chrome:
                events = call("trace_chrome", args.trace_id)
                if not events:
                    print(f"no stored trace matches {args.trace_id!r}",
                          file=sys.stderr)
                    return 1
                with open(args.chrome, "w") as f:
                    json.dump(events, f)
                print(f"wrote {len(events)} trace events to {args.chrome} "
                      f"(open in chrome://tracing or "
                      f"https://ui.perfetto.dev)")
                return 0
            detail = call("trace_get", args.trace_id)
            if detail is None:
                print(f"no stored trace matches {args.trace_id!r} (tail-"
                      f"sampling keeps errors/failovers/preempts/slow "
                      f"requests; see `trace_sample_rate`)",
                      file=sys.stderr)
                return 1
            print(_render_trace_tree(detail, verbose=args.verbose))
            return 0
        q = {"request_id": args.request or None,
             "session": args.session or None,
             "deployment": args.deployment or None,
             "slowest": args.slowest or None, "limit": args.limit}
        res = call("traces_query", q)
        for t in res.get("traces", ()):
            print(_fmt_trace_summary(t))
        if not args.follow:
            if not res.get("traces"):
                print("(no stored traces match)", file=sys.stderr)
            return 0
        cursor = res.get("cursor", 0)
        while True:
            res = call("traces_query",
                       {**q, "since": cursor, "follow_timeout": 10.0})
            cursor = res.get("cursor", cursor)
            for t in res.get("traces", ()):
                print(_fmt_trace_summary(t))
    except KeyboardInterrupt:
        return 0
    finally:
        closer()


def _cmd_stack(args) -> int:
    """`ray_tpu stack` — merged thread stacks of the driver and every
    live worker (ref: `ray stack`)."""
    from .util.introspect import format_stacks

    if getattr(args, "address", ""):
        ch = _head_channel(args)
        try:
            report = ch.call("stack_report", {"timeout": args.timeout},
                             timeout=args.timeout + 30)
        finally:
            ch.close()
    else:
        from .core import runtime as runtime_mod

        if runtime_mod.maybe_runtime() is None:
            return _no_runtime_help()
        from .util import state

        report = state.stack_report(timeout=args.timeout)
    drv = report.get("driver") or {}
    print(format_stacks(drv, header=f"=== driver pid={drv.get('pid')} ==="))
    workers = report.get("workers", [])
    for w in workers:
        head = (f"=== worker {w.get('worker_id', '')[:12]} "
                f"pid={w.get('pid')} node={w.get('node_id', '')[:8]} "
                f"state={w.get('state')}"
                + (f" actor={w['actor_id'][:8]}" if w.get("actor_id")
                   else "") + " ===")
        if w.get("error"):
            print(f"{head}\n  <no stacks: {w['error']}>")
        else:
            print(format_stacks(w, header=head))
    print(f"--- {len(workers)} worker(s), "
          f"{sum(1 for w in workers if w.get('error'))} unresponsive ---")
    return 0


def _cmd_profile(args) -> int:
    """`ray_tpu profile --worker ID [--duration S]` — on-demand sampling
    profile of one worker; prints a pstats-style table and (with
    --output) writes flamegraph collapsed-stack text."""
    from .util.introspect import collapsed_to_text, profile_to_text

    payload = {"worker_id": args.worker, "duration_s": args.duration,
               "interval_s": args.interval}
    if getattr(args, "address", ""):
        ch = _head_channel(args)
        try:
            res = ch.call("profile_worker", payload,
                          timeout=args.duration + 60)
        finally:
            ch.close()
    else:
        from .core import runtime as runtime_mod

        if runtime_mod.maybe_runtime() is None:
            return _no_runtime_help()
        from .util import state

        res = state.profile_worker(args.worker, duration_s=args.duration,
                                   interval_s=args.interval)
    print(f"worker {res.get('worker_id', '')[:12]} "
          f"node={res.get('node_id', '')[:8]} pid={res.get('pid')}")
    print(profile_to_text(res, top=args.top))
    if args.output:
        with open(args.output, "w") as f:
            f.write(collapsed_to_text(res) + "\n")
        print(f"wrote collapsed stacks to {args.output} "
              f"(flamegraph.pl / speedscope input)")
    return 0


def _cmd_status(args) -> int:
    from .core import runtime as runtime_mod

    rt = runtime_mod.maybe_runtime()
    if rt is None:
        print("No ray_tpu runtime in this process. `status` reports on the "
              "in-process cluster; run it from the driver, or see the head "
              "process logs for cluster membership.", file=sys.stderr)
        return 1
    for info in rt.gcs.nodes():
        state = "ALIVE" if info.alive else "DEAD"
        print(f"{info.node_id.hex()[:12]}  {state:5s}  {info.total_resources}")
    return 0


def _head_channel(args):
    from .core.rpc import connect

    if args.authkey:
        os.environ["RTPU_AUTHKEY"] = args.authkey
    host, sep, port = args.address.rpartition(":")
    if not sep or not host or not port.isdigit():
        print(f"--address must be HOST:PORT, got {args.address!r}",
              file=sys.stderr)
        raise SystemExit(2)
    return connect((host, int(port)), name="job-client")


def _cmd_submit(args) -> int:
    # strip only the LEADING '--' separator; later '--' tokens belong to
    # the entrypoint itself (e.g. `pytest tests -- -k foo`)
    entry = list(args.entrypoint)
    if entry and entry[0] == "--":
        entry = entry[1:]
    if not entry:
        print("submit needs an entrypoint after --", file=sys.stderr)
        return 2
    import shlex

    ch = _head_channel(args)
    try:
        job_id = ch.call("submit_job", {
            "entrypoint": shlex.join(entry),
            "env": json.loads(args.env),
            "working_dir": args.working_dir}, timeout=60)
        print(f"submitted {job_id}")
        if args.no_wait:
            return 0
        deadline = time.monotonic() + args.timeout
        while time.monotonic() < deadline:
            rec = ch.call("job_info", job_id, timeout=30) or {}
            if rec.get("status") in ("SUCCEEDED", "FAILED", "STOPPED"):
                logs = rec.get("logs", "")
                if logs:
                    sys.stdout.write(logs)
                print(f"job {job_id}: {rec['status']} "
                      f"(exit_code={rec.get('exit_code')})")
                return int(rec.get("exit_code") or 0) \
                    if rec["status"] != "SUCCEEDED" else 0
            time.sleep(0.5)
        print(f"timed out waiting for {job_id}", file=sys.stderr)
        return 1
    finally:
        ch.close()


def _cmd_job(args) -> int:
    ch = _head_channel(args)
    try:
        if args.what == "list":
            for rec in ch.call("list_jobs", None, timeout=30):
                print(f"{rec['job_id']}  {rec.get('status'):10s}  "
                      f"{rec.get('entrypoint', '')}")
            return 0
        if not args.job_id:
            print("job {status,logs,stop} needs a job id", file=sys.stderr)
            return 2
        if args.what == "status":
            rec = ch.call("job_info", args.job_id, timeout=30)
            print("NOT_FOUND" if rec is None else rec.get("status"))
            return 0 if rec else 1
        if args.what == "logs":
            rec = ch.call("job_info", args.job_id, timeout=30) or {}
            sys.stdout.write(rec.get("logs", ""))
            return 0
        ok = ch.call("stop_job", args.job_id, timeout=30)
        print("stopped" if ok else "not running")
        return 0
    finally:
        ch.close()


def _cmd_serve(args) -> int:
    """serve deploy/status/shutdown as a remote driver against a running
    head (client.py). A head is required: an in-process cluster would die
    with the CLI, taking the deployments with it."""
    if not args.address:
        print("serve commands need --address HOST:PORT of a running head\n"
              "(an in-process cluster would vanish when this CLI exits; "
              "for local experiments use serve.run/serve.deploy_config "
              "from a driver script)", file=sys.stderr)
        return 2
    from .client import connect_client

    if args.authkey:
        os.environ["RTPU_AUTHKEY"] = args.authkey
    connect_client(args.address)
    from ray_tpu import serve

    if args.what == "deploy":
        if not args.config:
            print("serve deploy needs a config file", file=sys.stderr)
            return 2
        out = serve.deploy_config(args.config)
        for n in out["deployments"]:
            print(f"deployed {n}")
        if out["http"]:
            print(f"http ingress on {out['http'][0]}:{out['http'][1]}")
        return 0
    try:
        if args.what == "status":
            for name, st in serve.status().items():
                print(f"{name:30s} {st['status']:10s} "
                      f"replicas={st.get('replicas')}")
            return 0
        serve.shutdown()
        print("serve shut down")
        return 0
    except ValueError:
        # get_actor raises ValueError when the controller doesn't exist;
        # anything else (auth, network) should surface as a traceback
        print("no serve instance running on this cluster", file=sys.stderr)
        return 1


def _render_top(snap: dict, prev, interval: float) -> str:
    """One refresh of the `ray_tpu top` table from a perf_snapshot."""
    lines = []
    t = time.strftime("%H:%M:%S", time.localtime(snap.get("time", 0)))
    nodes = snap.get("nodes", [])
    alive = sum(1 for n in nodes if n.get("alive"))
    lines.append(f"ray_tpu top — {t} — {alive}/{len(nodes)} node(s) alive")
    for n in nodes:
        res = " ".join(f"{k}={v:g}" for k, v in
                       sorted((n.get("resources") or {}).items()))
        lines.append(f"  {n.get('node_id', '')[:12]:12s}  "
                     f"{'ALIVE' if n.get('alive') else 'DEAD ':5s}  {res}")
    scalars = snap.get("scalars") or {}
    ok = scalars.get("ray_tpu_serve_slo_ok_total", {})
    bad = scalars.get("ray_tpu_serve_slo_violated_total", {})
    if ok or bad:
        lines.append("")
        lines.append("deployment SLO (ray_tpu_serve_slo_*_total):")
        for tag in sorted(set(ok) | set(bad)):
            o, v = ok.get(tag, 0.0), bad.get(tag, 0.0)
            pct = 100.0 * o / (o + v) if o + v else 100.0
            name = tag.split("=", 1)[1] if "=" in tag else (tag or "-")
            lines.append(f"  {name:28s} ok={o:<10.0f} violated={v:<8.0f} "
                         f"({pct:.1f}% within SLO)")
    traces = snap.get("traces")
    if traces:
        lines.append("")
        slow = traces.get("slowest_active")
        lines.append(
            f"tracing: kept={traces.get('traces', 0)} "
            f"active={traces.get('active', 0)} "
            f"dropped(sampled={traces.get('dropped_sampled', 0)} "
            f"evicted={traces.get('dropped_evicted', 0)})"
            + (f"  slowest-active={slow['trace_id']} "
               f"({slow['name']} {slow['age_s']:.1f}s) — "
               f"`ray_tpu trace {slow['trace_id'][:12]}`" if slow else ""))
    lines.append("")
    lines.append(f"  {'series':44s} {'tags':26s} {'value':>12s} "
                 f"{'rate/s':>9s}")
    prev_scalars = (prev or {}).get("scalars") or {}
    for fam in sorted(scalars):
        for tag, val in sorted(scalars[fam].items()):
            rate = ""
            pv = prev_scalars.get(fam, {}).get(tag)
            if pv is not None and interval > 0 and val >= pv:
                rate = f"{(val - pv) / interval:.1f}"
            lines.append(f"  {fam:44s} {tag or '-':26s} {val:>12g} "
                         f"{rate:>9s}")
    hist = snap.get("histograms") or {}
    if hist:
        def ms(x):
            return "-" if x is None else f"{x * 1e3:.2f}"

        lines.append("")
        lines.append(f"  {'histogram':44s} {'count':>8s} {'mean_ms':>9s} "
                     f"{'p50_ms':>9s} {'p95_ms':>9s} {'p99_ms':>9s}")
        for name, s in sorted(hist.items()):
            lines.append(f"  {name:44s} {s.get('count', 0):>8d} "
                         f"{ms(s.get('mean')):>9s} {ms(s.get('p50')):>9s} "
                         f"{ms(s.get('p95')):>9s} {ms(s.get('p99')):>9s}")
    return "\n".join(lines)


def _cmd_top(args) -> int:
    """`ray_tpu top [--interval S] [--once]` — refreshing cluster table:
    nodes, every ray_tpu_* scalar with its rate, latency summaries, and
    per-deployment SLO counters. ONE head RPC per refresh."""
    if args.address:
        ch = _head_channel(args)
        fetch = lambda: ch.call("perf_snapshot", {}, timeout=30)  # noqa: E731
        closer = ch.close
    else:
        from .core import runtime as runtime_mod

        rt = runtime_mod.maybe_runtime()
        if rt is None:
            return _no_runtime_help()
        from .perf.snapshot import head_snapshot

        fetch = lambda: head_snapshot(rt)  # noqa: E731
        closer = lambda: None  # noqa: E731
    prev = None
    try:
        while True:
            snap = fetch()
            text = _render_top(snap, prev, args.interval)
            if args.once:
                print(text)
                return 0
            sys.stdout.write("\x1b[2J\x1b[H" + text + "\n")
            sys.stdout.flush()
            prev = snap
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0
    finally:
        closer()


def _cmd_postmortem(args) -> int:
    """`ray_tpu postmortem [bundle.json]` — render a flight-recorder
    post-mortem bundle: metadata, in-flight (begin-without-end) ops per
    process, and the merged event tail. With no path, renders the most
    recent bundle in the dump directory."""
    from .perf.postmortem import (bundle_dir, last_bundle_path,
                                  load_bundle, render_bundle)

    path = args.bundle
    if not path:
        path = last_bundle_path()
        if path is None:
            print(f"no post-mortem bundles in {bundle_dir()} "
                  f"(set RAY_TPU_POSTMORTEM_DIR to look elsewhere)",
                  file=sys.stderr)
            return 1
    bundle = load_bundle(path)
    print(f"bundle: {path}")
    print(render_bundle(bundle, tail=args.tail))
    return 0


def _cmd_up(args) -> int:
    from .autoscaler.launcher import cluster_up

    state = cluster_up(args.config)
    print(f"cluster {state['cluster_name']} is up")
    print(f"  head: {state['address']}")
    print(f"  workers: {len(state['worker_pids'])}")
    print(f"  connect: ray_tpu serve/submit --address {state['address']} "
          f"--authkey {state['authkey']}")
    return 0


def _cmd_down(args) -> int:
    from .autoscaler.launcher import cluster_down

    cluster_down(args.cluster)
    print(f"cluster {args.cluster} torn down")
    return 0


def _cmd_attach(args) -> int:
    from .autoscaler.launcher import attach_cmd

    argv, env = attach_cmd(args.cluster)
    os.execvpe(argv[0], argv, {**os.environ, **env})


def _cmd_exec(args) -> int:
    from .autoscaler.launcher import exec_on_head

    cmd = list(args.cmd)
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        print("exec needs a command after --", file=sys.stderr)
        return 2
    import shlex

    sys.stdout.write(exec_on_head(args.cluster, shlex.join(cmd)))
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="ray_tpu", description=__doc__)
    sub = p.add_subparsers(dest="command", required=True)

    sp = sub.add_parser("start", help="start a head or join as a node agent")
    sp.add_argument("--head", action="store_true")
    sp.add_argument("--address", default="",
                    help="head HOST:PORT to join as an agent")
    sp.add_argument("--host", default="127.0.0.1")
    sp.add_argument("--port", type=int, default=6380)
    sp.add_argument("--num-cpus", type=float,
                    default=float(os.cpu_count() or 1))
    sp.add_argument("--resources", default="{}")
    sp.add_argument("--labels", default="{}")
    sp.add_argument("--authkey", default="",
                    help="cluster auth token (hex) printed by the head")
    sp.add_argument("--cluster-name", default="",
                    help="label only: lets the launcher find this "
                         "cluster's processes without putting the "
                         "authkey in argv")
    sp.set_defaults(fn=_cmd_start)

    st = sub.add_parser("status", help="show cluster nodes")
    st.add_argument("--address", default="")
    st.set_defaults(fn=_cmd_status)

    ls = sub.add_parser(
        "list", help="list tasks/actors/objects/nodes/pgs/summary/latency "
                     "(run from the driver process)")
    ls.add_argument("what", choices=["tasks", "actors", "objects", "nodes",
                                     "pgs", "summary", "latency"])
    ls.set_defaults(fn=_cmd_list)

    tl = sub.add_parser("timeline", help="export Chrome-trace of task events")
    tl.add_argument("--output", default="/tmp/ray_tpu_timeline.json")
    tl.set_defaults(fn=_cmd_timeline)

    lg = sub.add_parser(
        "logs", help="query/stream the cluster's attributed worker logs "
                     "(ref: `ray logs`); from the driver process or with "
                     "--address against a running head")
    lg.add_argument("--follow", "-f", action="store_true",
                    help="keep streaming new lines (long-poll)")
    lg.add_argument("--task", default="", help="task id (hex prefix)")
    lg.add_argument("--actor", default="", help="actor id (hex prefix)")
    lg.add_argument("--worker", default="", help="worker id (hex prefix)")
    lg.add_argument("--node", default="", help="node id (hex prefix)")
    lg.add_argument("--job", default="", help="job id (hex prefix)")
    lg.add_argument("--stream", default="",
                    choices=["", "stdout", "stderr", "log"])
    lg.add_argument("--errors", action="store_true",
                    help="only stderr lines and WARNING+ structured logs")
    lg.add_argument("--limit", type=int, default=200)
    lg.add_argument("--address", default="",
                    help="head HOST:PORT (omit for the in-process head)")
    lg.add_argument("--authkey", default="")
    lg.set_defaults(fn=_cmd_logs)

    tr = sub.add_parser(
        "trace", help="render a stored request trace's span tree, or "
                      "list tail-kept traces (--request/--session/"
                      "--slowest); ids accept unique hex prefixes, e.g. "
                      "off a /metrics exemplar")
    tr.add_argument("trace_id", nargs="?", default="",
                    help="trace id (hex prefix) to render as a span tree")
    tr.add_argument("--request", default="", help="filter by request id")
    tr.add_argument("--session", default="", help="filter by session id")
    tr.add_argument("--deployment", default="",
                    help="filter by deployment name")
    tr.add_argument("--slowest", type=int, default=0,
                    help="show the N slowest kept traces")
    tr.add_argument("--limit", type=int, default=50)
    tr.add_argument("--follow", "-f", action="store_true",
                    help="keep streaming newly kept traces (long-poll)")
    tr.add_argument("--chrome", default="",
                    help="with a trace id: write chrome://tracing JSON "
                         "here instead of rendering the tree")
    tr.add_argument("--verbose", "-v", action="store_true",
                    help="show every span attribute, not just the "
                         "common columns")
    tr.add_argument("--address", default="",
                    help="head HOST:PORT (omit for the in-process head)")
    tr.add_argument("--authkey", default="")
    tr.set_defaults(fn=_cmd_trace)

    sk = sub.add_parser(
        "stack", help="dump merged thread stacks of the driver and every "
                      "live worker (ref: `ray stack`)")
    sk.add_argument("--timeout", type=float, default=5.0)
    sk.add_argument("--address", default="",
                    help="head HOST:PORT (omit for the in-process head)")
    sk.add_argument("--authkey", default="")
    sk.set_defaults(fn=_cmd_stack)

    pf = sub.add_parser(
        "profile", help="on-demand sampling profile of one worker "
                        "(pstats-style table + flamegraph collapsed "
                        "stacks)")
    pf.add_argument("--worker", required=True,
                    help="worker id (hex prefix; see `ray_tpu stack`)")
    pf.add_argument("--duration", type=float, default=5.0)
    pf.add_argument("--interval", type=float, default=0.01)
    pf.add_argument("--top", type=int, default=25)
    pf.add_argument("--output", default="",
                    help="write flamegraph collapsed-stack text here")
    pf.add_argument("--address", default="")
    pf.add_argument("--authkey", default="")
    pf.set_defaults(fn=_cmd_profile)

    sj = sub.add_parser(
        "submit", help="run an entrypoint command as a job on a running "
                       "head (ref: job_manager.py submit_job)")
    sj.add_argument("--address", required=True, help="head HOST:PORT")
    sj.add_argument("--authkey", default="",
                    help="cluster auth token (hex) printed by the head")
    sj.add_argument("--working-dir", default=None)
    sj.add_argument("--env", default="{}",
                    help="extra env vars for the entrypoint, as JSON")
    sj.add_argument("--no-wait", action="store_true",
                    help="print the job id and return immediately")
    sj.add_argument("--timeout", type=float, default=3600.0)
    sj.add_argument("entrypoint", nargs=argparse.REMAINDER,
                    help="command to run (prefix with -- )")
    sj.set_defaults(fn=_cmd_submit)

    jb = sub.add_parser("job", help="status/logs/stop/list for jobs")
    jb.add_argument("what", choices=["status", "logs", "stop", "list"])
    jb.add_argument("job_id", nargs="?", default="")
    jb.add_argument("--address", required=True)
    jb.add_argument("--authkey", default="")
    jb.set_defaults(fn=_cmd_job)

    up = sub.add_parser("up", help="launch a cluster from a YAML config "
                                   "(ref: autoscaler commands.py "
                                   "create_or_update_cluster)")
    up.add_argument("config")
    up.set_defaults(fn=_cmd_up)

    dn = sub.add_parser("down", help="tear a launched cluster down")
    dn.add_argument("cluster", help="cluster name or config path")
    dn.set_defaults(fn=_cmd_down)

    at = sub.add_parser("attach", help="open a shell on the head node")
    at.add_argument("cluster", help="cluster name or config path")
    at.set_defaults(fn=_cmd_attach)

    ex = sub.add_parser("exec", help="run a command on the head node")
    ex.add_argument("cluster", help="cluster name or config path")
    ex.add_argument("cmd", nargs=argparse.REMAINDER)
    ex.set_defaults(fn=_cmd_exec)

    sv = sub.add_parser(
        "serve", help="deploy/status/shutdown serve applications "
                      "(ref: `serve deploy` + serve/schema.py config)")
    sv.add_argument("what", choices=["deploy", "status", "shutdown"])
    sv.add_argument("config", nargs="?", default="",
                    help="YAML/JSON application config (deploy)")
    sv.add_argument("--address", default="",
                    help="head HOST:PORT of a running cluster (required)")
    sv.add_argument("--authkey", default="")
    sv.set_defaults(fn=_cmd_serve)

    tp = sub.add_parser(
        "top", help="refreshing cluster perf table: nodes, ray_tpu_* "
                    "series with rates, latency summaries, SLO counters")
    tp.add_argument("--interval", type=float, default=2.0)
    tp.add_argument("--once", action="store_true",
                    help="print one snapshot and exit (no screen clear)")
    tp.add_argument("--address", default="",
                    help="head HOST:PORT (omit for the in-process head)")
    tp.add_argument("--authkey", default="")
    tp.set_defaults(fn=_cmd_top)

    pm = sub.add_parser(
        "postmortem", help="render a flight-recorder post-mortem bundle "
                           "(most recent when no path is given)")
    pm.add_argument("bundle", nargs="?", default="",
                    help="bundle JSON path (default: newest in the dump "
                         "directory)")
    pm.add_argument("--tail", type=int, default=40,
                    help="merged event lines to show")
    pm.set_defaults(fn=_cmd_postmortem)

    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
