"""Command-line interface (ref: python/ray/scripts/scripts.py:71
`ray start/stop/status`).

`ray_tpu start --head --port P`    — standalone head: hosts GCS + the head
                                     node and listens for joining agents.
`ray_tpu start --address H:P`      — node agent joining a head (the remote
                                     half of the multi-host runtime).
`ray_tpu status --address H:P`     — print cluster nodes/resources.

Usage: python -m ray_tpu <command> [options]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time


def _cmd_start(args) -> int:
    if args.head:
        from .core import runtime as runtime_mod
        from .core.runtime import DriverRuntime

        resources = {"CPU": args.num_cpus, **json.loads(args.resources)}
        rt = DriverRuntime(resources=resources)
        runtime_mod.set_runtime(rt)
        from .core.rpc import cluster_token

        addr = rt.enable_remote_nodes(host=args.host, port=args.port)
        print(f"ray_tpu head listening on {addr[0]}:{addr[1]}")
        print(f"Join more nodes with:\n  python -m ray_tpu start "
              f"--address {addr[0]}:{addr[1]} "
              f"--authkey {cluster_token().hex()}")
        try:
            while True:
                time.sleep(1.0)
        except KeyboardInterrupt:
            rt.shutdown()
        return 0
    if not args.address:
        print("start needs --head or --address HOST:PORT", file=sys.stderr)
        return 2
    from .core.node_agent import main as agent_main

    agent_args = ["--address", args.address,
                  "--num-cpus", str(args.num_cpus),
                  "--resources", args.resources,
                  "--labels", args.labels]
    if args.authkey:
        agent_args += ["--authkey", args.authkey]
    return agent_main(agent_args)


def _no_runtime_help() -> int:
    print("No ray_tpu runtime in this process. `list`/`timeline` read the "
          "in-process head state — call them from the driver (e.g. "
          "ray_tpu.cli.main(['list', 'summary'])) or use the state API "
          "(ray_tpu.util.state) directly.", file=sys.stderr)
    return 1


def _cmd_list(args) -> int:
    from .core import runtime as runtime_mod
    from .util import state

    if runtime_mod.maybe_runtime() is None:
        return _no_runtime_help()
    if args.what == "latency":
        _print_latency_table(state.latency_summary())
        return 0
    fn = {"nodes": state.list_nodes, "actors": state.list_actors,
          "tasks": state.list_tasks, "objects": state.list_objects,
          "pgs": state.list_placement_groups,
          "summary": state.summary}[args.what]
    rows = fn()
    print(json.dumps(rows, indent=2, default=str))
    return 0


def _print_latency_table(summary: dict) -> None:
    """Aligned p50/p95/p99 table per latency histogram (cluster-wide:
    worker/agent-shipped series are already merged in)."""
    cols = ("histogram", "count", "mean_ms", "p50_ms", "p95_ms", "p99_ms")

    def ms(v):
        return "-" if v is None else f"{v * 1e3:.2f}"

    rows = [(name, str(s["count"]), ms(s["mean"]), ms(s["p50"]),
             ms(s["p95"]), ms(s["p99"]))
            for name, s in sorted(summary.items(),
                                  key=lambda kv: -kv[1]["count"])]
    widths = [max(len(c), *(len(r[i]) for r in rows)) if rows else len(c)
              for i, c in enumerate(cols)]
    print("  ".join(c.ljust(w) for c, w in zip(cols, widths)))
    for r in rows:
        print("  ".join(v.ljust(w) for v, w in zip(r, widths)))
    if not rows:
        print("(no latency observations yet)")


def _cmd_timeline(args) -> int:
    from .core import runtime as runtime_mod
    from .util.state import timeline

    if runtime_mod.maybe_runtime() is None:
        return _no_runtime_help()
    events = timeline(output_path=args.output)
    print(f"wrote {len(events)} trace events to {args.output} "
          f"(open in chrome://tracing or https://ui.perfetto.dev)")
    return 0


def _cmd_status(args) -> int:
    from .core import runtime as runtime_mod

    rt = runtime_mod.maybe_runtime()
    if rt is None:
        print("No ray_tpu runtime in this process. `status` reports on the "
              "in-process cluster; run it from the driver, or see the head "
              "process logs for cluster membership.", file=sys.stderr)
        return 1
    for info in rt.gcs.nodes():
        state = "ALIVE" if info.alive else "DEAD"
        print(f"{info.node_id.hex()[:12]}  {state:5s}  {info.total_resources}")
    return 0


def _head_channel(args):
    from .core.rpc import connect

    if args.authkey:
        os.environ["RTPU_AUTHKEY"] = args.authkey
    host, sep, port = args.address.rpartition(":")
    if not sep or not host or not port.isdigit():
        print(f"--address must be HOST:PORT, got {args.address!r}",
              file=sys.stderr)
        raise SystemExit(2)
    return connect((host, int(port)), name="job-client")


def _cmd_submit(args) -> int:
    # strip only the LEADING '--' separator; later '--' tokens belong to
    # the entrypoint itself (e.g. `pytest tests -- -k foo`)
    entry = list(args.entrypoint)
    if entry and entry[0] == "--":
        entry = entry[1:]
    if not entry:
        print("submit needs an entrypoint after --", file=sys.stderr)
        return 2
    import shlex

    ch = _head_channel(args)
    try:
        job_id = ch.call("submit_job", {
            "entrypoint": shlex.join(entry),
            "env": json.loads(args.env),
            "working_dir": args.working_dir}, timeout=60)
        print(f"submitted {job_id}")
        if args.no_wait:
            return 0
        deadline = time.monotonic() + args.timeout
        while time.monotonic() < deadline:
            rec = ch.call("job_info", job_id, timeout=30) or {}
            if rec.get("status") in ("SUCCEEDED", "FAILED", "STOPPED"):
                logs = rec.get("logs", "")
                if logs:
                    sys.stdout.write(logs)
                print(f"job {job_id}: {rec['status']} "
                      f"(exit_code={rec.get('exit_code')})")
                return int(rec.get("exit_code") or 0) \
                    if rec["status"] != "SUCCEEDED" else 0
            time.sleep(0.5)
        print(f"timed out waiting for {job_id}", file=sys.stderr)
        return 1
    finally:
        ch.close()


def _cmd_job(args) -> int:
    ch = _head_channel(args)
    try:
        if args.what == "list":
            for rec in ch.call("list_jobs", None, timeout=30):
                print(f"{rec['job_id']}  {rec.get('status'):10s}  "
                      f"{rec.get('entrypoint', '')}")
            return 0
        if not args.job_id:
            print("job {status,logs,stop} needs a job id", file=sys.stderr)
            return 2
        if args.what == "status":
            rec = ch.call("job_info", args.job_id, timeout=30)
            print("NOT_FOUND" if rec is None else rec.get("status"))
            return 0 if rec else 1
        if args.what == "logs":
            rec = ch.call("job_info", args.job_id, timeout=30) or {}
            sys.stdout.write(rec.get("logs", ""))
            return 0
        ok = ch.call("stop_job", args.job_id, timeout=30)
        print("stopped" if ok else "not running")
        return 0
    finally:
        ch.close()


def _cmd_serve(args) -> int:
    """serve deploy/status/shutdown as a remote driver against a running
    head (client.py). A head is required: an in-process cluster would die
    with the CLI, taking the deployments with it."""
    if not args.address:
        print("serve commands need --address HOST:PORT of a running head\n"
              "(an in-process cluster would vanish when this CLI exits; "
              "for local experiments use serve.run/serve.deploy_config "
              "from a driver script)", file=sys.stderr)
        return 2
    from .client import connect_client

    if args.authkey:
        os.environ["RTPU_AUTHKEY"] = args.authkey
    connect_client(args.address)
    from ray_tpu import serve

    if args.what == "deploy":
        if not args.config:
            print("serve deploy needs a config file", file=sys.stderr)
            return 2
        out = serve.deploy_config(args.config)
        for n in out["deployments"]:
            print(f"deployed {n}")
        if out["http"]:
            print(f"http ingress on {out['http'][0]}:{out['http'][1]}")
        return 0
    try:
        if args.what == "status":
            for name, st in serve.status().items():
                print(f"{name:30s} {st['status']:10s} "
                      f"replicas={st.get('replicas')}")
            return 0
        serve.shutdown()
        print("serve shut down")
        return 0
    except ValueError:
        # get_actor raises ValueError when the controller doesn't exist;
        # anything else (auth, network) should surface as a traceback
        print("no serve instance running on this cluster", file=sys.stderr)
        return 1


def _cmd_up(args) -> int:
    from .autoscaler.launcher import cluster_up

    state = cluster_up(args.config)
    print(f"cluster {state['cluster_name']} is up")
    print(f"  head: {state['address']}")
    print(f"  workers: {len(state['worker_pids'])}")
    print(f"  connect: ray_tpu serve/submit --address {state['address']} "
          f"--authkey {state['authkey']}")
    return 0


def _cmd_down(args) -> int:
    from .autoscaler.launcher import cluster_down

    cluster_down(args.cluster)
    print(f"cluster {args.cluster} torn down")
    return 0


def _cmd_attach(args) -> int:
    from .autoscaler.launcher import attach_cmd

    argv, env = attach_cmd(args.cluster)
    os.execvpe(argv[0], argv, {**os.environ, **env})


def _cmd_exec(args) -> int:
    from .autoscaler.launcher import exec_on_head

    cmd = list(args.cmd)
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        print("exec needs a command after --", file=sys.stderr)
        return 2
    import shlex

    sys.stdout.write(exec_on_head(args.cluster, shlex.join(cmd)))
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="ray_tpu", description=__doc__)
    sub = p.add_subparsers(dest="command", required=True)

    sp = sub.add_parser("start", help="start a head or join as a node agent")
    sp.add_argument("--head", action="store_true")
    sp.add_argument("--address", default="",
                    help="head HOST:PORT to join as an agent")
    sp.add_argument("--host", default="127.0.0.1")
    sp.add_argument("--port", type=int, default=6380)
    sp.add_argument("--num-cpus", type=float,
                    default=float(os.cpu_count() or 1))
    sp.add_argument("--resources", default="{}")
    sp.add_argument("--labels", default="{}")
    sp.add_argument("--authkey", default="",
                    help="cluster auth token (hex) printed by the head")
    sp.add_argument("--cluster-name", default="",
                    help="label only: lets the launcher find this "
                         "cluster's processes without putting the "
                         "authkey in argv")
    sp.set_defaults(fn=_cmd_start)

    st = sub.add_parser("status", help="show cluster nodes")
    st.add_argument("--address", default="")
    st.set_defaults(fn=_cmd_status)

    ls = sub.add_parser(
        "list", help="list tasks/actors/objects/nodes/pgs/summary/latency "
                     "(run from the driver process)")
    ls.add_argument("what", choices=["tasks", "actors", "objects", "nodes",
                                     "pgs", "summary", "latency"])
    ls.set_defaults(fn=_cmd_list)

    tl = sub.add_parser("timeline", help="export Chrome-trace of task events")
    tl.add_argument("--output", default="/tmp/ray_tpu_timeline.json")
    tl.set_defaults(fn=_cmd_timeline)

    sj = sub.add_parser(
        "submit", help="run an entrypoint command as a job on a running "
                       "head (ref: job_manager.py submit_job)")
    sj.add_argument("--address", required=True, help="head HOST:PORT")
    sj.add_argument("--authkey", default="",
                    help="cluster auth token (hex) printed by the head")
    sj.add_argument("--working-dir", default=None)
    sj.add_argument("--env", default="{}",
                    help="extra env vars for the entrypoint, as JSON")
    sj.add_argument("--no-wait", action="store_true",
                    help="print the job id and return immediately")
    sj.add_argument("--timeout", type=float, default=3600.0)
    sj.add_argument("entrypoint", nargs=argparse.REMAINDER,
                    help="command to run (prefix with -- )")
    sj.set_defaults(fn=_cmd_submit)

    jb = sub.add_parser("job", help="status/logs/stop/list for jobs")
    jb.add_argument("what", choices=["status", "logs", "stop", "list"])
    jb.add_argument("job_id", nargs="?", default="")
    jb.add_argument("--address", required=True)
    jb.add_argument("--authkey", default="")
    jb.set_defaults(fn=_cmd_job)

    up = sub.add_parser("up", help="launch a cluster from a YAML config "
                                   "(ref: autoscaler commands.py "
                                   "create_or_update_cluster)")
    up.add_argument("config")
    up.set_defaults(fn=_cmd_up)

    dn = sub.add_parser("down", help="tear a launched cluster down")
    dn.add_argument("cluster", help="cluster name or config path")
    dn.set_defaults(fn=_cmd_down)

    at = sub.add_parser("attach", help="open a shell on the head node")
    at.add_argument("cluster", help="cluster name or config path")
    at.set_defaults(fn=_cmd_attach)

    ex = sub.add_parser("exec", help="run a command on the head node")
    ex.add_argument("cluster", help="cluster name or config path")
    ex.add_argument("cmd", nargs=argparse.REMAINDER)
    ex.set_defaults(fn=_cmd_exec)

    sv = sub.add_parser(
        "serve", help="deploy/status/shutdown serve applications "
                      "(ref: `serve deploy` + serve/schema.py config)")
    sv.add_argument("what", choices=["deploy", "status", "shutdown"])
    sv.add_argument("config", nargs="?", default="",
                    help="YAML/JSON application config (deploy)")
    sv.add_argument("--address", default="",
                    help="head HOST:PORT of a running cluster (required)")
    sv.add_argument("--authkey", default="")
    sv.set_defaults(fn=_cmd_serve)

    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
