"""Remote-driver client — drive a running cluster from another process.

Equivalent of the reference's Ray Client (ref: python/ray/util/client/ —
client-side api.py/worker.py speaking to server/proxier.py on the head).
`ray_tpu.init(address="HOST:PORT")` returns a ClientRuntime: the full
core API (remote/get/put/wait/actors/PGs/KV) proxied over one duplex
channel to the head, so the cluster outlives any number of drivers.
Object payloads travel as bytes — a remote process cannot map the
head's /dev/shm segments — which is exactly the reference's client
data-plane behavior (client objects are server-resident, ids travel)."""
from __future__ import annotations

from typing import Any, List, Optional

from . import exceptions as exc
from .core import serialization
from .core.ids import ObjectId, WorkerId
from .core.object_ref import ObjectRef
from .core.runtime import WorkerRuntime


class _ClientChannelShim:
    """The `worker_process` surface WorkerRuntime expects (channel +
    worker identity); reader is absent — clients never touch segments."""

    def __init__(self, channel, worker_id: WorkerId):
        self.channel = channel
        self.worker_id = worker_id
        self.reader = None


class ClientRuntime(WorkerRuntime):
    """WorkerRuntime over a TCP channel to the head, with byte-valued
    object transfer instead of shared-memory attach."""

    is_client = True

    def __init__(self, address: str, authkey: Optional[str] = None):
        import os

        from .core.rpc import connect

        host, _, port = address.rpartition(":")
        if not host or not port.isdigit():
            raise ValueError(f"address must be HOST:PORT, got {address!r}")
        if authkey:
            os.environ["RTPU_AUTHKEY"] = authkey
        channel = connect((host, int(port)), name="client")
        hello = channel.call("register_client", {}, timeout=30)
        super().__init__(_ClientChannelShim(
            channel, WorkerId.from_hex(hello["client_id"])))
        # Remote drivers never take the direct dispatch path: the client
        # object plane is head-resident (client_get_objects below), so a
        # direct result landing in this process would be invisible to the
        # client's own get(); a cross-host client couldn't reach a
        # worker's direct unix socket anyway. Every client call routes
        # through the head, which submits it direct on the client's
        # behalf when eligible.
        self._direct = None
        self._hello = hello

    # -- object plane: bytes over the wire --------------------------------

    def put(self, value: Any) -> ObjectRef:
        oid = self.next_put_id()
        sobj = serialization.serialize(value)
        self.channel.call("client_put", {"object_id": oid,
                                         "data": sobj.to_bytes()})
        ref = ObjectRef(oid)
        self.adopt_owned_ref(ref)
        return ref

    def get_many(self, oids: List[ObjectId],
                 timeout: Optional[float] = None):
        results = self.channel.call(
            "client_get_objects", {"ids": oids, "timeout": timeout},
            timeout=None)
        return [self._deserialize(res) for res in results]

    def _deserialize(self, res):
        value = serialization.loads(res[1])
        if isinstance(value, exc.TaskError):
            cause = value.cause
            if isinstance(cause, exc.RayTpuError):
                raise cause
            raise value
        if isinstance(value, exc.RayTpuError):
            raise value
        return value

    def shutdown(self) -> None:
        try:
            self.channel.close()
        except Exception:
            pass


def connect_client(address: str,
                   authkey: Optional[str] = None) -> ClientRuntime:
    return ClientRuntime(address, authkey=authkey)
