"""In-process multi-node cluster for tests.

Equivalent of the reference's ray.cluster_utils.Cluster
(ref: python/ray/cluster_utils.py:99; add_node :165, remove_node :238) — the
standard way fault-tolerance tests create and kill "nodes" without machines.
Each added node is a full Node (raylet-equivalent) with its own shared-memory
store and worker subprocesses.
"""
from __future__ import annotations

from typing import Dict, Optional

from .core import runtime as runtime_mod
from .core.config import Config
from .core.node import Node
from .core.runtime import DriverRuntime


class Cluster:
    def __init__(self, initialize_head: bool = True,
                 head_resources: Optional[Dict[str, float]] = None,
                 system_config: Optional[dict] = None):
        if runtime_mod.maybe_runtime() is not None:
            raise RuntimeError("ray_tpu already initialized")
        res = head_resources or {"CPU": 2.0}
        self.runtime = DriverRuntime(resources=res, num_nodes=1 if initialize_head else 0,
                                     config=Config(system_config))
        runtime_mod.set_runtime(self.runtime)
        self.head_node = (next(iter(self.runtime.nodes.values()))
                          if initialize_head else None)

    def add_node(self, num_cpus: float = 2.0, num_tpus: float = 0.0,
                 resources: Optional[Dict[str, float]] = None,
                 labels: Optional[Dict[str, str]] = None) -> Node:
        res = dict(resources or {})
        res.setdefault("CPU", num_cpus)
        if num_tpus:
            res["TPU"] = num_tpus
        return self.runtime.add_node(res, labels)

    def remove_node(self, node: Node, kill: bool = True) -> None:
        """kill=True simulates abrupt node failure (workers SIGKILLed, object
        store segments destroyed) — the chaos-test path."""
        self.runtime.remove_node(node.node_id, kill=kill)

    def shutdown(self) -> None:
        self.runtime.shutdown()
        runtime_mod.set_runtime(None)
