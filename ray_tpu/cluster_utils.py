"""In-process multi-node cluster for tests.

Equivalent of the reference's ray.cluster_utils.Cluster
(ref: python/ray/cluster_utils.py:99; add_node :165, remove_node :238) — the
standard way fault-tolerance tests create and kill "nodes" without machines.
Each added node is a full Node (raylet-equivalent) with its own shared-memory
store and worker subprocesses.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time
from typing import Dict, Optional

from .core import runtime as runtime_mod
from .core.config import Config
from .core.node import Node
from .core.runtime import DriverRuntime


class Cluster:
    def __init__(self, initialize_head: bool = True,
                 head_resources: Optional[Dict[str, float]] = None,
                 system_config: Optional[dict] = None):
        if runtime_mod.maybe_runtime() is not None:
            raise RuntimeError("ray_tpu already initialized")
        res = head_resources or {"CPU": 2.0}
        self.runtime = DriverRuntime(resources=res, num_nodes=1 if initialize_head else 0,
                                     config=Config(system_config))
        runtime_mod.set_runtime(self.runtime)
        self.head_node = (next(iter(self.runtime.nodes.values()))
                          if initialize_head else None)

    def add_node(self, num_cpus: float = 2.0, num_tpus: float = 0.0,
                 resources: Optional[Dict[str, float]] = None,
                 labels: Optional[Dict[str, str]] = None) -> Node:
        res = dict(resources or {})
        res.setdefault("CPU", num_cpus)
        if num_tpus:
            res["TPU"] = num_tpus
        return self.runtime.add_node(res, labels)

    def add_remote_node(self, num_cpus: float = 2.0,
                        resources: Optional[Dict[str, float]] = None,
                        labels: Optional[Dict[str, str]] = None,
                        timeout: float = 30.0) -> Node:
        """Start a node agent in a SEPARATE OS process that joins over
        localhost TCP — the multi-host path (ref: cluster_utils.py
        add_node runs real raylets; here: ray_tpu.core.node_agent)."""
        from .core.ids import NodeId

        addr = self.runtime.enable_remote_nodes()
        node_id = NodeId.from_random()  # assigned here so the join is
        res = dict(resources or {})     # matched deterministically
        res.setdefault("CPU", num_cpus)
        env = dict(os.environ)
        env["PYTHONPATH"] = os.pathsep.join(p for p in sys.path if p)
        proc = subprocess.Popen(
            [sys.executable, "-S", "-m", "ray_tpu.core.node_agent",
             "--address", f"{addr[0]}:{addr[1]}",
             "--num-cpus", str(res.pop("CPU")),
             "--resources", json.dumps(res),
             "--labels", json.dumps(labels or {}),
             "--node-id", node_id.hex()],
            env=env)
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            node = self.runtime.nodes.get(node_id)
            if node is not None:
                node._agent_proc = proc  # for remove_node(kill=True)
                return node
            if proc.poll() is not None:
                raise RuntimeError(
                    f"node agent exited rc={proc.returncode} before joining")
            time.sleep(0.05)
        proc.kill()
        raise TimeoutError("node agent did not join in time")

    def remove_node(self, node: Node, kill: bool = True) -> None:
        """kill=True simulates abrupt node failure (workers SIGKILLed, object
        store segments destroyed) — the chaos-test path. For a remote node
        with kill=True the agent process is SIGKILLed, exercising the
        channel-loss path."""
        proc = getattr(node, "_agent_proc", None)
        if proc is not None and kill:
            proc.kill()
            try:
                proc.wait(timeout=10)
            except Exception:
                pass
            self.runtime.on_remote_node_lost(node.node_id)
            return
        self.runtime.remove_node(node.node_id, kill=kill)
        if proc is not None:
            try:
                proc.wait(timeout=10)
            except Exception:
                proc.kill()

    def shutdown(self) -> None:
        self.runtime.shutdown()
        runtime_mod.set_runtime(None)
