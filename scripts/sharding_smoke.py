#!/usr/bin/env python
"""CI smoke for the sharded execution layer (ISSUE 11 /
docs/SHARDING.md).

Live gate on a forced 2-host-device mesh:

- serve tp: an LLMServer replica with ``tp=2`` runs its prefill/decode
  programs lowered under a 2-chip mesh in a REAL worker process while
  concurrent driver-side clients stream completions through the serve
  handle — every stream must be token-identical to the tp=1 ground
  truth, the per-chip KV occupancy gauge must account every pool block
  (sum(chips) == total, peak split across both chips), and the
  replica's KV bytes must be resident half-per-chip;
- train fsdp: a 2-device fsdp pipeline engine steps twice and must
  match the replicated (fsdp=1) engine's loss trajectory BITWISE, with
  per-chip param+opt bytes ~1/2 of the stage total.

Exit 0 = healthy; any assertion prints the evidence and exits 1.
Run: python scripts/sharding_smoke.py  (CI invokes it after chaos_smoke)
"""
import os
import sys
import threading
import time

# the tp/fsdp meshes need forced host devices BEFORE jax is imported
# anywhere in this process tree (replica workers inherit the env)
os.environ["JAX_PLATFORMS"] = "cpu"
if "--xla_force_host_platform_device_count" not in \
        os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=2")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

ENGINE_CFG = dict(block_size=4, num_blocks=64, max_batch=4,
                  max_blocks_per_seq=8, prefill_buckets=(8, 16))
N_CLIENTS = 4
MAX_TOKENS = 10


def reference_completions(prompts):
    """tp=1 greedy ground truth from a driver-local engine over the
    same seed-0 weights the tp=2 replica builds."""
    from ray_tpu.serve.llm import EngineConfig, LLMEngine, build_model

    m, params = build_model("gpt-tiny")
    eng = LLMEngine(m, params, EngineConfig(**ENGINE_CFG))
    out = []
    for p in prompts:
        st = eng.add_request(p, max_tokens=MAX_TOKENS)
        eng.run_until_idle(timeout=300)
        out.append(st.tokens())
    eng.pool.check_leaks()
    return out


def serve_tp_smoke() -> None:
    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.serve.llm import LLMServer

    prompts = [[1 + i, 5, 9] for i in range(N_CLIENTS)]
    want = reference_completions(prompts)
    assert all(len(w) == MAX_TOKENS for w in want), want

    app = serve.deployment(
        num_replicas=1, health_check_timeout_s=180)(LLMServer).bind(
        model="gpt-tiny", engine_config={**ENGINE_CFG, "tp": 2})
    handle = serve.run(app, timeout=300)

    got = [None] * N_CLIENTS
    errs = []

    def client(i):
        try:
            gen = handle.options(stream=True).remote(
                {"tokens": prompts[i], "max_tokens": MAX_TOKENS,
                 "stream": True})
            got[i] = [tok for tok in gen]
        except Exception as e:  # noqa: BLE001 — report, don't hang
            errs.append((i, e))

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(N_CLIENTS)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=300)
    wall = time.perf_counter() - t0
    assert not errs, f"client errors: {errs}"
    for i, (g, w) in enumerate(zip(got, want)):
        assert g == w, (f"client {i}: tp=2 stream != tp=1 ground "
                        f"truth:\n  got  {g}\n  want {w}")
    print(f"sharding_smoke: {N_CLIENTS} tp=2 streaming clients "
          f"token-identical to tp=1 in {wall:.2f}s")

    stats = ray_tpu.get(handle.stats.remote(), timeout=60)
    assert stats["tp"] == 2, stats
    assert stats["kv_blocks_used"] == 0, f"leaked blocks: {stats}"
    peak = stats["kv_blocks_peak_per_chip"]
    assert len(peak) == 2 and sum(peak) >= N_CLIENTS, \
        f"per-chip peak occupancy does not cover the burst: {stats}"
    assert min(peak) > 0, \
        f"blocks never landed on one chip (not block-sharded?): {stats}"
    byts = stats["kv_bytes_per_chip"]
    assert len(byts) == 2 and len(set(byts.values())) == 1, \
        f"KV cache not resident half-per-chip: {byts}"
    print(f"sharding_smoke: per-chip KV accounting OK "
          f"(peak {peak}, {next(iter(byts.values()))} bytes/chip)")
    serve.shutdown()


def train_fsdp_smoke() -> None:
    import jax
    import jax.numpy as jnp
    import optax

    from ray_tpu.train.pipeline_cgraph import CompiledPipelineEngine

    k = jax.random.PRNGKey(0)
    width, M = 16, 4

    def mk_mid():
        def fn(p, x):
            return jnp.tanh(x @ p["w"] + p["b"])
        return fn

    def mk_last():
        def fn(p, x, targets):
            return jnp.mean((x @ p["w"] + p["b"] - targets) ** 2)
        return fn

    fns = [mk_mid(), mk_last()]
    params = [
        {"w": jax.random.normal(jax.random.fold_in(k, i),
                                (width, width)) * 0.3,
         "b": jnp.zeros((width,))}
        for i in range(2)]
    xs = jax.random.normal(jax.random.fold_in(k, 7), (M * 2, width))
    ys = jax.random.normal(jax.random.fold_in(k, 8), (M * 2, width))
    mbs = [xs[i * 2:(i + 1) * 2] for i in range(M)]
    tgts = [ys[i * 2:(i + 1) * 2] for i in range(M)]

    losses = {}
    per_chip = None
    for fsdp in (1, 2):
        eng = CompiledPipelineEngine(fns, params, optax.adam(1e-2),
                                     num_microbatches=M, fsdp=fsdp,
                                     channel_bytes=1 << 18)
        try:
            losses[fsdp] = [eng.step(mbs, tgts) for _ in range(2)]
            if fsdp == 2:
                per_chip = [r["fsdp_bytes_per_chip"]
                            for r in eng.last_reports]
        finally:
            eng.shutdown()
    assert losses[2] == losses[1], \
        f"fsdp=2 trajectory diverged: {losses[2]} != {losses[1]}"
    for stage_chips in per_chip:
        vals = list(stage_chips.values())
        assert len(vals) == 2, per_chip
        assert max(vals) <= sum(vals) / 2 + 64, \
            f"per-chip bytes not ~1/fsdp: {per_chip}"
    print(f"sharding_smoke: fsdp=2 pipeline bitwise == replicated "
          f"({losses[2]}), per-chip bytes {per_chip}")


def main() -> int:
    import ray_tpu

    ray_tpu.init(num_cpus=4)
    try:
        serve_tp_smoke()
        train_fsdp_smoke()
    finally:
        try:
            ray_tpu.shutdown()
        except Exception:
            pass
    print("sharding_smoke: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
