#!/usr/bin/env python
"""CI smoke for decentralized dispatch (ISSUE 6 / docs/DISPATCH.md).

Spins up an in-process head plus one REAL remote node agent (a second OS
process over localhost TCP), pins an actor on each node, and pushes a
call burst through the direct path, asserting:

- results are correct for every call on both actors (zero lost results)
- >0 calls went DIRECT (driver -> local worker over its channel, and
  driver -> remote worker over the peer direct socket)
- steady state makes zero routed submissions
- severing the cached peer connection mid-burst falls back to the head
  with no lost results, then the direct path re-establishes
- a worker-side caller reaches a remote actor directly
- teardown is clean (cluster shuts down, agent exits)

Exit 0 = healthy; any assertion prints the evidence and exits 1.
Run: python scripts/dispatch_smoke.py   (CI invokes it after cgraph_smoke)
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    import ray_tpu
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.core.runtime import dispatch_counts
    from ray_tpu.util.scheduling_strategies import (
        NodeAffinitySchedulingStrategy)

    c = Cluster(head_resources={"CPU": 2.0})
    try:
        remote = c.add_remote_node(num_cpus=2.0)
        pin = NodeAffinitySchedulingStrategy(node_id=remote.node_id,
                                             soft=False)

        @ray_tpu.remote(num_cpus=0.1)
        class Acc:
            def __init__(self):
                self.n = 0

            def add(self, x):
                self.n += x
                return self.n

        local = Acc.remote()                                  # head node
        far = Acc.options(scheduling_strategy=pin).remote()   # remote node
        assert ray_tpu.get(local.add.remote(0), timeout=60) == 0
        assert ray_tpu.get(far.add.remote(0), timeout=60) == 0

        # -- steady-state burst: everything direct, nothing lost ---------
        d0, r0 = dispatch_counts()
        n = 100
        refs = [local.add.remote(1) for _ in range(n)]
        refs += [far.add.remote(1) for _ in range(n)]
        out = ray_tpu.get(refs, timeout=120)
        assert out[:n] == list(range(1, n + 1)), "local results lost"
        assert out[n:] == list(range(1, n + 1)), "remote results lost"
        d1, r1 = dispatch_counts()
        assert d1 - d0 == 2 * n, \
            f"expected {2*n} direct calls, got {d1 - d0}"
        assert r1 - r0 == 0, f"{r1 - r0} routed calls in steady state"
        print(f"dispatch-smoke: {2*n} calls all direct "
              f"(local worker channel + remote peer socket), 0 routed")

        # -- sever the remote peer connection mid-burst ------------------
        rt = c.runtime
        rec = rt._actors[far._actor_id]
        assert rec.direct_chan is not None, \
            "remote actor should be reached over a cached peer channel"
        refs = [far.add.remote(1) for _ in range(20)]
        rec.direct_chan.close()  # in-flight calls fall back via the head
        refs += [far.add.remote(1) for _ in range(20)]
        out = ray_tpu.get(refs, timeout=120)
        # every get resolves and no call is LOST; calls delivered but
        # unanswered when the connection dropped may re-run on the still-
        # alive actor (at-least-once — the same window routed
        # worker-crash retries have; docs/DISPATCH.md)
        assert len(out) == 40 and out[-1] >= n + 40, \
            f"lost results across the peer-failure fallback: {out[-1]}"
        print("dispatch-smoke: peer-connection drop fell back with "
              "zero lost results "
              f"({out[-1] - n - 40} duplicate side effects in the "
              "at-least-once window)")
        d2, _ = dispatch_counts()
        ray_tpu.get([far.add.remote(0) for _ in range(10)], timeout=60)
        d3, _ = dispatch_counts()
        assert d3 - d2 >= 10, "direct path did not re-establish after drop"
        print("dispatch-smoke: direct path re-established after the drop")

        # -- worker-side caller reaches the remote actor directly --------
        @ray_tpu.remote(num_cpus=0.1)
        def burst(handle, k):
            ray_tpu.get([handle.add.remote(0) for _ in range(k)],
                        timeout=120)
            from ray_tpu.core.runtime import dispatch_counts as dc

            return dc()

        wd, wr = ray_tpu.get(burst.remote(far, 25), timeout=120)
        assert wd >= 25 and wr == 0, \
            f"worker caller split direct={wd} routed={wr}"
        print("dispatch-smoke: worker-to-worker direct calls OK "
              f"(direct={int(wd)}, routed={int(wr)})")
    finally:
        c.shutdown()
    time.sleep(0.5)
    print("dispatch-smoke: clean teardown")
    return 0


if __name__ == "__main__":
    sys.exit(main())
