"""MFU sweep: full-train-step medians for candidate configs on the TPU.

Usage: python scripts/mfu_sweep.py [quick|full]
Prints one line per config: median sec/step, tokens/s, MFU.
"""
import functools
import statistics
import sys
import time

import jax
import jax.numpy as jnp
import optax

sys.path.insert(0, ".")
from ray_tpu.models import GPT, GPTConfig  # noqa: E402

PEAK = 197e12  # v5e bf16


def time_config(name, cfg, batch, loss_kind, steps=6, warmup=2,
                num_chunks=None):
    model = GPT(cfg)
    tx = optax.adamw(3e-4, weight_decay=0.1)
    params = jax.jit(model.init)(jax.random.PRNGKey(0))
    opt_state = jax.jit(tx.init)(params)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (batch, 1024), 0,
                                cfg.vocab_size)
    targets = jnp.roll(tokens, -1, axis=1)
    if loss_kind == "plain":
        loss_fn = model.loss
    elif num_chunks is None:
        loss_fn = model.loss_chunked
    else:
        import functools as _ft

        loss_fn = _ft.partial(model.loss_chunked, num_chunks=num_chunks)

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def step(params, opt_state, tokens, targets):
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens, targets)
        updates, opt_state = tx.update(grads, opt_state, params)
        return loss, optax.apply_updates(params, updates), opt_state

    for _ in range(warmup):
        loss, params, opt_state = step(params, opt_state, tokens, targets)
    float(loss)
    # time in chunks of `inner` steps with ONE host sync each (bench.py
    # style): a per-step sync would add a tunnel round-trip to every step
    inner = 5
    times = []
    for _ in range(steps):
        t0 = time.perf_counter()
        for _ in range(inner):
            loss, params, opt_state = step(params, opt_state, tokens, targets)
        float(loss)
        times.append((time.perf_counter() - t0) / inner)
    med = statistics.median(times)
    toks = batch * 1024 / med
    mfu = model.flops_per_token(1024) * toks / PEAK
    print(f"{name:44s} med={med*1000:7.1f}ms tok/s={toks:9.0f} mfu={mfu:.4f}",
          flush=True)
    return mfu


def main():
    mode = sys.argv[1] if len(sys.argv) > 1 else "quick"
    base = dict(dtype=jnp.bfloat16, use_flash=True)
    runs = [
        ("B16 flash1024 plain (r2 baseline)",
         GPTConfig.small(**base), 16, "plain"),
        ("B32 flash1024 chunked",
         GPTConfig.small(**base), 32, "chunked"),
        ("B32 flash1024 plain",
         GPTConfig.small(**base), 32, "plain"),
        ("B32 flash512q1024k chunked",
         GPTConfig.small(flash_block_q=512, **base), 32, "chunked"),
        ("B32 flash512q512k chunked",
         GPTConfig.small(flash_block_q=512, flash_block_k=512, **base),
         32, "chunked"),
        ("B32 noflash chunked",
         GPTConfig.small(dtype=jnp.bfloat16, use_flash=False), 32, "chunked"),
        ("B16 noflash plain",
         GPTConfig.small(dtype=jnp.bfloat16, use_flash=False), 16, "plain"),
    ]
    if mode == "full":
        runs += [
            ("B24 flash chunked", GPTConfig.small(**base), 24, "chunked"),
            ("B48 flash chunked", GPTConfig.small(**base), 48, "chunked"),
            ("B32 flash chunked noremat",
             GPTConfig.small(remat=False, **base), 32, "chunked"),
            ("B16 flash plain noremat",
             GPTConfig.small(remat=False, **base), 16, "plain"),
        ]
    if mode == "r3b":
        un = dict(scan_layers=False, **base)
        nc = lambda b, rows: (b * 1024) // rows  # noqa: E731
        runs = [
            ("b32 noremat c4096 (r3 best)",
             GPTConfig.small(remat=False, **un), 32, "chunked", nc(32, 4096)),
            ("b16 noremat c4096",
             GPTConfig.small(remat=False, **un), 16, "chunked", nc(16, 4096)),
            ("b24 noremat c4096",
             GPTConfig.small(remat=False, **un), 24, "chunked", nc(24, 4096)),
            ("b32 noremat 512x1024",
             GPTConfig.small(remat=False, flash_block_q=512, **un),
             32, "chunked", nc(32, 4096)),
            ("b32 noremat plain-loss",
             GPTConfig.small(remat=False, **un), 32, "plain", None),
            ("b16 noremat plain-loss",
             GPTConfig.small(remat=False, **un), 16, "plain", None),
            ("b48 noremat c4096",
             GPTConfig.small(remat=False, **un), 48, "chunked", nc(48, 4096)),
            ("b32 noremat scan",
             GPTConfig.small(remat=False, scan_layers=True, **base),
             32, "chunked", nc(32, 4096)),
        ]
        for name, cfg, b, kind, chunks in runs:
            try:
                time_config(name, cfg, b, kind, num_chunks=chunks)
            except Exception as e:
                print(f"{name:44s} FAILED: {type(e).__name__}: "
                      f"{str(e)[:140]}", flush=True)
        return
    if mode == "r3":
        runs = []
        un = dict(scan_layers=False, **base)
        nc = lambda b, rows: (b * 1024) // rows  # noqa: E731
        runs = [
            ("b64 1024x1024 c4096 (bench now)",
             GPTConfig.small(**un), 64, "chunked", nc(64, 4096)),
            ("b64 512x512 c4096",
             GPTConfig.small(flash_block_q=512, flash_block_k=512, **un),
             64, "chunked", nc(64, 4096)),
            ("b64 512x1024 c4096",
             GPTConfig.small(flash_block_q=512, **un),
             64, "chunked", nc(64, 4096)),
            ("b64 256x512 c4096",
             GPTConfig.small(flash_block_q=256, flash_block_k=512, **un),
             64, "chunked", nc(64, 4096)),
            ("b96 1024x1024 c4096",
             GPTConfig.small(**un), 96, "chunked", nc(96, 4096)),
            ("b64 1024x1024 c8192",
             GPTConfig.small(**un), 64, "chunked", nc(64, 8192)),
            ("b64 1024x1024 c16384",
             GPTConfig.small(**un), 64, "chunked", nc(64, 16384)),
            ("b64 1024x1024 c2048",
             GPTConfig.small(**un), 64, "chunked", nc(64, 2048)),
            ("b32 noremat c4096",
             GPTConfig.small(remat=False, **un), 32, "chunked", nc(32, 4096)),
            ("b64 noremat c4096",
             GPTConfig.small(remat=False, **un), 64, "chunked", nc(64, 4096)),
            ("b48 1024x1024 c4096",
             GPTConfig.small(**un), 48, "chunked", nc(48, 4096)),
        ]
        for name, cfg, b, kind, chunks in runs:
            try:
                time_config(name, cfg, b, kind, num_chunks=chunks)
            except Exception as e:
                print(f"{name:44s} FAILED: {type(e).__name__}: "
                      f"{str(e)[:140]}", flush=True)
        return
    for name, cfg, b, kind in runs:
        try:
            time_config(name, cfg, b, kind)
        except Exception as e:
            print(f"{name:44s} FAILED: {type(e).__name__}: {e}", flush=True)


if __name__ == "__main__":
    main()
