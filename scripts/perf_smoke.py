#!/usr/bin/env python
"""CI smoke for the performance-introspection plane (ISSUE 17).

Spins up an in-process head plus one REAL remote node agent, then:

- profiles a 2-stage `CompiledPipelineEngine` split across the node
  boundary: StepReport phases (compute/bubble/send) must sum to ~the
  measured step wall (within 10%), the chrome-trace export must be
  loadable JSON with schema-valid events from BOTH stage processes,
  and `suggest()` must return strings
- profiles a CONCURRENT llm stream: engine on its background thread,
  streaming clients in flight, `profile()` observing passively — the
  admit/prefill/decode/retire phase split must likewise sum to ~the
  profiled steps' wall, with occupancy/kv-pressure series populated
- fetches one `ray_tpu top` snapshot over the SAME head RPC the CLI
  uses (`perf_snapshot`) and renders it: 2 alive nodes, the pipeline
  step histogram present
- A/B overhead gate: median step time with the flight recorder on vs
  off (toggled driver+workers via `set_flight_recording`), interleaved
  rounds so box drift cancels; the bar is load/CPU-aware like the
  tier-1 envelope test

Exit 0 = healthy; any assertion prints the evidence and exits 1.
Run: python scripts/perf_smoke.py   (CI invokes it after traffic_smoke)
"""
import json
import os
import statistics
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _mlp(num_chunks: int, width: int, M: int, mb_size: int):
    import jax
    import jax.numpy as jnp

    k = jax.random.PRNGKey(0)

    def mk_mid():
        def fn(p, x):
            return jnp.tanh(x @ p["w"] + p["b"])
        return fn

    def mk_last():
        def fn(p, x, targets):
            return jnp.mean((x @ p["w"] + p["b"] - targets) ** 2)
        return fn

    fns = [mk_mid() for _ in range(num_chunks - 1)] + [mk_last()]
    params = [
        {"w": jax.random.normal(jax.random.fold_in(k, i),
                                (width, width)) * 0.3,
         "b": jnp.zeros((width,))}
        for i in range(num_chunks)]
    xs = jax.random.normal(jax.random.fold_in(k, 5), (M * mb_size, width))
    ys = jax.random.normal(jax.random.fold_in(k, 6), (M * mb_size, width))
    mbs = [xs[i * mb_size:(i + 1) * mb_size] for i in range(M)]
    tgts = [ys[i * mb_size:(i + 1) * mb_size] for i in range(M)]
    return fns, params, mbs, tgts


def _check_chrome_trace(trace: dict) -> int:
    # round-trip through JSON: perfetto loads the serialized form
    trace = json.loads(json.dumps(trace))
    assert isinstance(trace, dict) and "traceEvents" in trace, trace
    events = trace["traceEvents"]
    assert isinstance(events, list) and events, "empty chrome trace"
    for ev in events:
        assert isinstance(ev, dict), f"non-dict event: {ev!r}"
        want = ("ph", "name", "pid", "tid") if ev.get("ph") == "M" \
            else ("ph", "name", "pid", "tid", "ts")
        missing = [k for k in want if k not in ev]
        assert not missing, f"event missing {missing}: {ev}"
    complete = [ev for ev in events if ev["ph"] == "X"]
    assert complete, "no complete ('X') span events in trace"
    for ev in complete:
        assert isinstance(ev.get("dur"), (int, float)) and ev["dur"] > 0, \
            f"X event without positive dur: {ev}"
    op_tids = {ev["tid"] for ev in complete
               if ev.get("cat") == "cgraph"
               and (ev.get("args") or {}).get("method")
               in ("forward", "backward")}
    assert len(op_tids) >= 2, \
        f"expected op spans from both stage lanes, tids={op_tids}"
    return len(events)


def main() -> int:
    import optax

    import ray_tpu  # noqa: F401 — Cluster below owns init
    from ray_tpu import cli
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.core.rpc import connect
    from ray_tpu.train import CompiledPipelineEngine
    from ray_tpu.util.scheduling_strategies import (
        NodeAffinitySchedulingStrategy)

    c = Cluster(head_resources={"CPU": 2.0})
    try:
        remote = c.add_remote_node(num_cpus=2.0)

        # -- 1) pipeline profile across the node boundary ---------------
        M, mb = 8, 4
        fns, params, mbs, tgts = _mlp(2, 16, M=M, mb_size=mb)
        eng = CompiledPipelineEngine(
            fns, params, optax.sgd(0.05), num_microbatches=M,
            channel_bytes=1 << 18,
            scheduling_strategies=[
                NodeAffinitySchedulingStrategy(node_id=c.runtime.head_node_id,
                                               soft=False),
                NodeAffinitySchedulingStrategy(node_id=remote.node_id,
                                               soft=False)])
        eng.step(mbs, tgts)   # compile + prime channels
        rep = eng.profile(steps=4, tokens_per_step=M * mb,
                          flops_per_token=1.0e6, peak_flops=1.0e12)
        ratio = rep.phase_wall_ratio()
        assert abs(ratio - 1.0) <= 0.10, \
            (f"pipeline phases !~ step wall: ratio={ratio:.3f} "
             f"phases={rep.phases} mean_step={rep.mean_step_ms:.2f}ms")
        assert 0.0 < rep.bubble_frac < 1.0, f"bubble_frac={rep.bubble_frac}"
        assert rep.tokens_per_s > 0 and rep.mfu > 0, \
            f"tokens_per_s={rep.tokens_per_s} mfu={rep.mfu}"
        assert {s["stage"] for s in rep.stages} == {"0.0", "0.1"}, \
            f"stage rows: {[s['stage'] for s in rep.stages]}"
        n_ev = _check_chrome_trace(rep.to_chrome_trace())
        hints = rep.suggest()
        assert hints and all(isinstance(h, str) for h in hints), hints
        print(f"pipeline profile OK: ratio={ratio:.3f} "
              f"bubble={rep.bubble_frac:.3f} mfu={rep.mfu:.2e} "
              f"trace_events={n_ev} hints={len(hints)}")

        # -- 2) overhead A/B, interleaved rounds, load-aware bar --------
        def timed(n=3):
            t0 = time.perf_counter()
            for _ in range(n):
                eng.step(mbs, tgts)
            return (time.perf_counter() - t0) / n

        ratios = []
        for _ in range(4):
            on_s = timed()
            eng.set_flight_recording(False)
            try:
                off_s = timed()
            finally:
                eng.set_flight_recording(True)
            ratios.append(on_s / off_s)
        overhead_pct = (statistics.median(ratios) - 1.0) * 100
        ncpu = os.cpu_count() or 2
        try:
            load = os.getloadavg()[0] / ncpu
        except OSError:
            load = 0.0
        bar = 10.0 if (ncpu >= 4 and load < 0.75) else 25.0
        assert overhead_pct <= bar, \
            (f"recorder overhead {overhead_pct:.1f}% > {bar}% bar "
             f"(ncpu={ncpu} load={load:.2f} rounds={ratios})")
        print(f"overhead A/B OK: {overhead_pct:+.1f}% "
              f"(bar {bar}%, ncpu={ncpu}, load {load:.2f})")
        eng.shutdown()

        # -- 3) concurrent llm stream, passive profile ------------------
        from ray_tpu.serve.llm import EngineConfig, LLMEngine, build_model

        m, params2 = build_model("gpt-tiny")
        leng = LLMEngine(m, params2, EngineConfig(
            max_batch=4, num_blocks=64, block_size=8,
            max_blocks_per_seq=8, prefill_buckets=(8, 16),
            max_prefill_tokens_per_step=64), name="perf-smoke")
        warm = leng.add_request([1, 2, 3], max_tokens=2)
        leng.run_until_idle(timeout=600)
        warm.tokens()
        leng.start()
        stop_feed = threading.Event()
        fed = []

        def feeder():
            i = 0
            while not stop_feed.is_set():
                # keep a few streams in flight so every profiled step
                # has admissions or decodes to account for
                live = [s for s in fed if s.finish_reason is None]
                if len(live) < 4:
                    fed.append(leng.add_request(
                        [1 + (i % 50), 5, 9, 2], max_tokens=24))
                    i += 1
                time.sleep(0.002)

        th = threading.Thread(target=feeder, daemon=True)
        th.start()
        try:
            lrep = leng.profile(steps=8, timeout=60.0)
        finally:
            stop_feed.set()
            th.join(2.0)
            for s in fed:
                s.tokens(timeout=60)
            leng.stop()
        lratio = lrep.phase_wall_ratio()
        assert abs(lratio - 1.0) <= 0.10, \
            (f"llm phases !~ step wall: ratio={lratio:.3f} "
             f"phases={lrep.phases} steps={lrep.steps}")
        assert lrep.tokens_per_s > 0, f"tokens_per_s={lrep.tokens_per_s}"
        assert lrep.occupancy and max(lrep.occupancy) <= 4, lrep.occupancy
        assert lrep.kv_pressure and all(0 <= p <= 1
                                        for p in lrep.kv_pressure), \
            lrep.kv_pressure
        print(f"llm profile OK: ratio={lratio:.3f} "
              f"tokens/s={lrep.tokens_per_s:.0f} "
              f"occ_max={max(lrep.occupancy)} "
              f"phases={lrep.phases}")

        # -- 4) `ray_tpu top` snapshot over the CLI's own head RPC ------
        addr = c.runtime.enable_remote_nodes()
        ch = connect(addr, name="perf-smoke-top")
        snap = ch.call("perf_snapshot", {}, timeout=30)
        alive = [n for n in snap["nodes"] if n["alive"]]
        assert len(alive) >= 2, f"nodes: {snap['nodes']}"
        assert "ray_tpu_pipeline_step_seconds" in snap["histograms"], \
            f"histograms: {sorted(snap['histograms'])[:20]}"
        rendered = cli._render_top(snap, None, 2.0)
        assert "ray_tpu_pipeline_step_seconds" in rendered \
            and "nodes" in rendered, rendered[:400]
        print(f"top snapshot OK: {len(alive)} alive nodes, "
              f"{len(snap['scalars'])} scalar families, "
              f"{len(snap['histograms'])} histogram families")
        print("perf smoke OK")
        return 0
    finally:
        c.shutdown()


if __name__ == "__main__":
    sys.exit(main())
