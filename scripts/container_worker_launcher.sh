#!/bin/bash
# Reference docker launcher for containerized workers
# (ref: python/ray/_private/runtime_env/container.py — podman there).
#
# Invoked by the node/agent as:
#   container_worker_launcher.sh <image> [run_options...] -- <cmd...>
#
# The worker talks to its node over a unix socket and shared-memory
# segments, so the container must share the host's network/IPC/pid
# namespaces and see the session directory; RTPU_AUTHKEY and PYTHONPATH
# ride the environment. Swap this script (config.container_launcher)
# for podman/nerdctl/k8s equivalents.
set -eu

IMAGE="$1"; shift
OPTS=()
while [ $# -gt 0 ] && [ "$1" != "--" ]; do
    OPTS+=("$1"); shift
done
[ $# -gt 0 ] && shift  # drop the --

exec docker run --rm \
    --network=host --ipc=host --pid=host \
    -e RTPU_AUTHKEY -e PYTHONPATH \
    -v /tmp:/tmp -v /dev/shm:/dev/shm \
    "${OPTS[@]+"${OPTS[@]}"}" "$IMAGE" "$@"
