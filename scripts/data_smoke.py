#!/usr/bin/env python
"""CI smoke for the streaming train-feed data plane (ISSUE 19).

Spins up an in-process head plus one REAL remote node agent (a second
OS process over localhost TCP) and drives the whole ingest->train path
on it. Gates:

- a from_numpy -> map_batches(ActorPoolStrategy) plan streams every row
  exactly once through remote preprocessing actors with the BYTE budget
  on (`peak_bytes_inflight` bounded, all blocks emitted)
- one `windowed_shuffle` epoch is a permutation and replays
  bit-identically at the same (seed, epoch)
- `Dataset.split_shards(2)` shards feed a dp=2 `CompiledPipelineEngine`
  via `attach_feed` for 10 steps: the loss trajectory is BIT-IDENTICAL
  to hand-feeding the same shard batches, and the steady-state fed
  steps make ZERO driver dispatches (`runtime.dispatch_counts()`)
- the three data-plane metric families
  (`ray_tpu_data_{bytes_inflight,blocks_emitted_total,
  feed_microbatches_total}`) land in a /metrics render — pump rows ride
  the throttled worker delta path
- engine shutdown returns every store's channel accounting to the
  pre-engine baseline — zero leaked segments on either node
- the bench rows (`bench_core.data_plane_bench`) hold their bars:
  `feed_vs_handfed_tokens_ratio` >= 0.95, ingest/shuffle rows non-zero

Exit 0 = healthy; any assertion prints the evidence and exits 1.
Run: python scripts/data_smoke.py   (CI invokes it after trace_smoke)
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("RTPU_BENCH_SMOKE", "1")  # bench_core reads at import

M = 4          # microbatches per replica per step
DP = 2
MB_SIZE = 2
WIDTH = 16
STEPS = 10


def _stage(width: int):
    import jax
    import jax.numpy as jnp

    k = jax.random.PRNGKey(3)

    def fn(p, x, targets):
        return jnp.mean((x @ p["w"] + p["b"] - targets) ** 2)

    param = {"w": jax.random.normal(k, (width, width)) * 0.3,
             "b": jnp.zeros((width,))}
    return [fn], [param]


def main() -> int:
    import numpy as np
    import optax

    import ray_tpu  # noqa: F401 — Cluster below owns init
    import ray_tpu.data as rd
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.core.runtime import dispatch_counts
    from ray_tpu.data import ActorPoolStrategy, DataContext, DataFeed
    from ray_tpu.train.pipeline_cgraph import CompiledPipelineEngine
    from ray_tpu.util import metrics

    c = Cluster(head_resources={"CPU": 4.0})
    try:
        c.add_remote_node(num_cpus=4.0)

        def store_channels() -> dict:
            return {nid: n.store.stats().get("num_channels", 0)
                    for nid, n in c.runtime.nodes.items()}

        baseline = store_channels()

        # 1) byte-budgeted ingest through remote preprocessing actors:
        # every row exactly once (in order — preserve_order default),
        # peak outstanding bytes bounded. 256 KiB blocks so the
        # store-reported sizes dominate the 64 KiB bootstrap estimate.
        rng = np.random.default_rng(0)
        big = rng.standard_normal((8 * 1024, 64)).astype(np.float32)
        block_bytes = big.nbytes // 8
        ctx = DataContext.get_current()
        old_budget = ctx.target_max_bytes_inflight
        ctx.target_max_bytes_inflight = 3 * block_bytes
        try:
            ds = rd.from_numpy({"x": big}, parallelism=8).map_batches(
                lambda b: {"x": np.tanh(b["x"]).astype(np.float32)},
                compute=ActorPoolStrategy(2))
            got = np.concatenate(
                [b["x"] for b in ds.iter_batches(batch_size=None)])
        finally:
            ctx.target_max_bytes_inflight = old_budget
        expect = np.tanh(big).astype(np.float32)
        assert got.shape == expect.shape and np.array_equal(got, expect), \
            "preprocessed stream is not the input rows in order"
        st = ds.stats()
        # read segment + actor-pool segment both emit -> 16 block emits
        assert st["blocks_emitted"] >= 16, st
        # two windows at ~3-4 blocks each; full materialization (16
        # blocks across both generations) must never be reached
        assert 0 < st["peak_bytes_inflight"] <= 10 * block_bytes, st
        print(f"byte-budgeted ingest OK ({st['blocks_emitted']} block "
              f"emits, peak {st['peak_bytes_inflight']} bytes)")

        # 2) windowed shuffle: one epoch is a permutation; same
        # (seed, epoch) replays bit-identically
        rows = 256
        base = rd.from_numpy({"x": np.arange(rows, dtype=np.int64)},
                             parallelism=8)
        sh = base.windowed_shuffle(window_blocks=4, seed=11)

        def drain():
            return np.concatenate(
                [b["x"] for b in sh.iter_batches(batch_size=None)])

        e0, e0b = drain(), drain()
        assert np.array_equal(np.sort(e0), np.arange(rows)), \
            "shuffle epoch is not a permutation"
        assert not np.array_equal(e0, np.arange(rows)), \
            "shuffle did not move any row"
        assert np.array_equal(e0, e0b), \
            "same (seed, epoch) must replay bit-identically"
        print("windowed shuffle OK (permutation, deterministic replay)")

        # 3) dp=2 engine fed via attach_feed from split_shards(2):
        # 10 fed steps, loss bit-identical to hand-feeding the same
        # shard batches, zero driver dispatches in steady state
        w_true = rng.standard_normal((WIDTH, WIDTH)).astype(np.float32) * 0.5
        # DP*M blocks of MB_SIZE rows: each block becomes exactly one
        # microbatch, each shard exactly M of them
        raw = rng.standard_normal(
            (DP * M * MB_SIZE, WIDTH)).astype(np.float32)
        feed_ds = rd.from_numpy({"x": raw}, parallelism=DP * M).map_batches(
            lambda b: {"x": np.tanh(b["x"]).astype(np.float32)},
            compute=ActorPoolStrategy(2))
        shards = feed_ds.split_shards(DP)

        def to_microbatches(shard, steps=STEPS + 1, w=w_true):
            def it():
                for _ in range(steps):
                    for b in shard.iter_batches(batch_size=MB_SIZE):
                        x = b["x"]
                        yield x, np.tanh(x @ w)
            return it()

        # the hand-fed reference consumes the SAME DataShard objects
        # driver-side, so the replayed arrays are bitwise the feed's
        mbs, tgts = [], []
        for shard in shards:
            for b in shard.iter_batches(batch_size=MB_SIZE):
                mbs.append(b["x"])
                tgts.append(np.tanh(b["x"] @ w_true))
        assert len(mbs) == DP * M, f"sharding produced {len(mbs)} mbs"

        fns, params = _stage(WIDTH)
        tx = optax.adam(1e-2)
        ref = CompiledPipelineEngine(fns, params, tx, num_microbatches=M,
                                     dp=DP, channel_bytes=1 << 18)
        try:
            ref_losses = [ref.step(mbs, tgts) for _ in range(STEPS)]
        finally:
            ref.shutdown()

        eng = CompiledPipelineEngine(fns, params, tx, num_microbatches=M,
                                     dp=DP, channel_bytes=1 << 18)
        try:
            eng.attach_feed(DataFeed.from_shards(shards, to_microbatches))
            losses = [eng.step()]
            d0, r0 = dispatch_counts()
            losses += [eng.step() for _ in range(STEPS - 1)]
            d1, r1 = dispatch_counts()
            assert losses == ref_losses, \
                f"fed != hand-fed: {losses} vs {ref_losses}"
            assert (d1 - d0, r1 - r0) == (0, 0), \
                f"steady-state fed steps dispatched ({d1 - d0}, {r1 - r0})"
            fst = eng.feed_stats()
            assert all(s["error"] is None for s in fst), fst
            assert all(s["sent"] >= STEPS * M for s in fst), fst
            print(f"fed dp=2 engine OK ({STEPS} steps bit-identical, "
                  f"0 driver dispatches, "
                  f"pumps sent {[s['sent'] for s in fst]})")

            # 4) the three data-plane metric families are scraped
            deadline = time.monotonic() + 15
            want = ("ray_tpu_data_bytes_inflight",
                    "ray_tpu_data_blocks_emitted_total",
                    "ray_tpu_data_feed_microbatches_total")
            body = metrics._render()
            while (not all(w in body for w in want)
                   and time.monotonic() < deadline):
                time.sleep(0.3)
                body = metrics._render()
            missing = [w for w in want if w not in body]
            assert not missing, f"missing metrics: {missing}"
            print("data metrics OK")
        finally:
            eng.shutdown()

        # 5) teardown leaked nothing on either node
        after = store_channels()
        assert after == baseline, \
            f"leaked channels: baseline={baseline} after={after}"
        print("shutdown channel accounting OK")
    finally:
        c.shutdown()

    # 6) bench rows hold their bars (docs/DATA.md methodology) — on a
    # fresh single-node runtime, same as `python bench.py --only data`;
    # best-of-2 on the ratio: it is a timing row and CI cores are
    # oversubscribed, but a starving pump tier fails BOTH attempts
    import ray_tpu
    from bench_core import data_plane_bench

    ray_tpu.init(num_cpus=max(4, os.cpu_count() or 4))
    try:
        rows_out = data_plane_bench()
        ratio = rows_out["feed_vs_handfed_tokens_ratio"]
        if ratio < 0.95:
            print(f"ratio {ratio} < 0.95, retrying once: {rows_out}")
            rows_out = data_plane_bench()
            ratio = max(ratio, rows_out["feed_vs_handfed_tokens_ratio"])
        assert ratio >= 0.95, \
            f"feed_vs_handfed_tokens_ratio {ratio} < 0.95: {rows_out}"
        assert rows_out["data_ingest_mb_s"] > 0, rows_out
        assert rows_out["shuffle_epoch_ms"] > 0, rows_out
        print(f"bench rows OK (ratio {ratio}, "
              f"ingest {rows_out['data_ingest_mb_s']} MB/s, "
              f"shuffle {rows_out['shuffle_epoch_ms']} ms)")
    finally:
        ray_tpu.shutdown()
    print("data smoke OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
