#!/usr/bin/env python
"""CI smoke for the continuous-batching LLM engine (ISSUE 7 /
docs/LLM_SERVE.md).

Live 2-process gate: an LLMServer deployment replica runs the engine in
a REAL worker process while concurrent driver-side clients stream
completions through the serve handle and the HTTP proxies, asserting:

- every streaming client receives its FULL greedy completion, in order,
  with zero lost or cross-request-interleaved tokens (ground truth = a
  driver-local engine over the same seeded weights)
- the NDJSON and SSE proxy framings carry the same tokens (and the SSE
  stream closes with its terminal `event: done` frame)
- the engine's `ray_tpu_llm_*` gauges/histograms crossed the worker ->
  head delta path and appear in a real /metrics scrape
- engine stats report zero leaked KV blocks after the burst

Exit 0 = healthy; any assertion prints the evidence and exits 1.
Run: python scripts/llm_smoke.py   (CI invokes it after dispatch_smoke)
"""
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

ENGINE_CFG = dict(block_size=4, num_blocks=64, max_batch=4,
                  max_blocks_per_seq=8, prefill_buckets=(8, 16))
N_CLIENTS = 6
MAX_TOKENS = 10


def reference_completions(prompts):
    """Ground-truth greedy completions from a driver-local engine over
    the same seed-0 weights the replica builds."""
    from ray_tpu.serve.llm import EngineConfig, LLMEngine, build_model

    m, params = build_model("gpt-tiny")
    eng = LLMEngine(m, params, EngineConfig(**ENGINE_CFG))
    out = []
    for p in prompts:
        st = eng.add_request(p, max_tokens=MAX_TOKENS)
        eng.run_until_idle(timeout=300)
        out.append(st.tokens())
    eng.pool.check_leaks()
    return out


def main() -> int:
    import urllib.request

    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.serve.llm import LLMServer
    from ray_tpu.util import metrics as metrics_mod

    prompts = [[1 + i, 5, 9] for i in range(N_CLIENTS)]
    want = reference_completions(prompts)
    assert all(len(w) == MAX_TOKENS for w in want), want

    ray_tpu.init(num_cpus=4)
    try:
        app = serve.deployment(
            num_replicas=1, health_check_timeout_s=120)(LLMServer).bind(
            model="gpt-tiny", engine_config=ENGINE_CFG)
        handle = serve.run(app, timeout=300)

        # -- concurrent streaming clients through the handle -------------
        got = [None] * N_CLIENTS
        errs = []

        def client(i):
            try:
                gen = handle.options(stream=True).remote(
                    {"tokens": prompts[i], "max_tokens": MAX_TOKENS,
                     "stream": True})
                got[i] = [tok for tok in gen]
            except Exception as e:  # noqa: BLE001 — report, don't hang
                errs.append((i, e))

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(N_CLIENTS)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
        wall = time.perf_counter() - t0
        assert not errs, f"client errors: {errs}"
        for i, (g, w) in enumerate(zip(got, want)):
            assert g == w, (f"client {i}: tokens lost/interleaved:\n"
                            f"  got  {g}\n  want {w}")
        print(f"llm_smoke: {N_CLIENTS} streaming clients x {MAX_TOKENS} "
              f"tokens OK in {wall:.2f}s "
              f"({N_CLIENTS * MAX_TOKENS / wall:.0f} tok/s aggregate)")

        # -- proxy framings: NDJSON + SSE over real HTTP ------------------
        host, port = serve.start_http_proxy(port=0)
        body = json.dumps({"tokens": prompts[0],
                           "max_tokens": MAX_TOKENS, "stream": True})
        with urllib.request.urlopen(urllib.request.Request(
                f"http://{host}:{port}/LLMServer?stream=1", body.encode(),
                {"Content-Type": "application/json"}), timeout=120) as r:
            ndjson = [json.loads(l) for l in
                      r.read().decode().strip().split("\n")]
        assert ndjson == want[0], f"NDJSON stream mismatch: {ndjson}"
        with urllib.request.urlopen(urllib.request.Request(
                f"http://{host}:{port}/LLMServer?stream=sse", body.encode(),
                {"Content-Type": "application/json"}), timeout=120) as r:
            raw = r.read().decode()
        frames = [f for f in raw.split("\n\n") if f.strip()]
        assert frames[-1].startswith("event: done"), frames[-1:]
        sse = [json.loads(f[len("data: "):]) for f in frames[:-1]]
        assert sse == want[0], f"SSE stream mismatch: {sse}"
        print("llm_smoke: NDJSON + SSE proxy framings OK")

        # -- engine state + metrics on the head scrape --------------------
        stats = ray_tpu.get(handle.stats.remote(), timeout=60)
        assert stats["kv_blocks_used"] == 0, f"leaked blocks: {stats}"
        # decode-step emissions only (the prefill's first token isn't a
        # decode iteration): 8 requests x (MAX_TOKENS - 1)
        assert stats["total_generated"] >= (N_CLIENTS + 2) * (MAX_TOKENS - 1)
        mhost, mport = metrics_mod.start_metrics_server()
        deadline = time.time() + 30
        scrape = ""
        while time.time() < deadline:  # wait for the worker delta ship
            with urllib.request.urlopen(
                    f"http://{mhost}:{mport}/metrics", timeout=10) as r:
                scrape = r.read().decode()
            if "ray_tpu_llm_ttft_seconds" in scrape:
                break
            time.sleep(0.5)
        for name in ("ray_tpu_llm_queue_depth", "ray_tpu_llm_kv_blocks_used",
                     "ray_tpu_llm_tokens_per_s", "ray_tpu_llm_ttft_seconds",
                     "ray_tpu_llm_tpot_seconds"):
            assert name in scrape, \
                f"{name} missing from the head /metrics scrape"
        print("llm_smoke: ray_tpu_llm_* metrics present on the head scrape")
        serve.shutdown()
    finally:
        ray_tpu.shutdown()
    print("llm_smoke: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
