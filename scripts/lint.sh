#!/usr/bin/env bash
# Static gate for the repo: graftcheck (framework-aware rules GC001-GC008,
# see docs/GRAFTCHECK.md) plus a bytecode-compile pass over the package.
# Usage: scripts/lint.sh [extra graftcheck paths...]
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== graftcheck =="
python -m ray_tpu.devtools.graftcheck ray_tpu/ examples/ tests/ "$@"

echo "== compileall =="
python -m compileall -q ray_tpu

echo "lint OK"
