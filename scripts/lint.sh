#!/usr/bin/env bash
# Static gate for the repo: the graftcheck whole-program engine (rules
# GC001-GC033, see docs/GRAFTCHECK.md — incl. the v3 CFG-based
# path-sensitive lifecycle pass) plus a bytecode-compile pass.
#
# The engine keeps a content-hash file cache (.graftcheck-cache.json,
# persisted across CI runs by actions/cache) so repeat runs only
# re-parse changed files; the CFG/dataflow pass runs at parse time, so
# warm runs skip it entirely. Two runs execute here: the first is cold
# on a fresh checkout (or warm when CI restored the cache), the second
# is always warm. Both are held to a timing budget so the engine's
# cost stays visible in CI (measured with the CFG pass: cold ~5.6s,
# warm ~0.7s on the CI box class — within the v2-era budgets, so they
# stay unraised), and --stats prints the CFG/fixpoint counters so
# analysis-cost regressions show up in CI logs:
#   run 1  < GRAFTCHECK_BUDGET_COLD_S  (default 10s)
#   run 2  < GRAFTCHECK_BUDGET_WARM_S  (default 3s, cache-served)
# Usage: scripts/lint.sh [extra graftcheck paths...]
set -euo pipefail
cd "$(dirname "$0")/.."

CACHE="${GRAFTCHECK_CACHE:-.graftcheck-cache.json}"

echo "== graftcheck (whole-program engine) =="
python - "$CACHE" "$@" <<'PY'
import os
import sys
import time

from ray_tpu.devtools.graftcheck import main

cache, extra = sys.argv[1], sys.argv[2:]
args = ["--cache", cache, "--stats",
        "ray_tpu/", "examples/", "tests/", *extra]
budget_cold = float(os.environ.get("GRAFTCHECK_BUDGET_COLD_S", "10"))
budget_warm = float(os.environ.get("GRAFTCHECK_BUDGET_WARM_S", "3"))

t0 = time.monotonic()
rc = main(args)
cold = time.monotonic() - t0
if rc != 0:
    sys.exit(rc)

t0 = time.monotonic()
rc = main(args)
warm = time.monotonic() - t0
if rc != 0:
    sys.exit(rc)

print(f"graftcheck timing: run1 {cold:.2f}s (budget {budget_cold:.0f}s), "
      f"warm {warm:.2f}s (budget {budget_warm:.0f}s)")
if cold > budget_cold or warm > budget_warm:
    print("graftcheck: TIMING BUDGET EXCEEDED", file=sys.stderr)
    sys.exit(3)
PY

echo "== compileall =="
python -m compileall -q ray_tpu

echo "lint OK"
