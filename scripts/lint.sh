#!/usr/bin/env bash
# Static gate for the repo: the graftcheck whole-program engine (rules
# GC001-GC054, see docs/GRAFTCHECK.md — incl. the v3 CFG-based
# path-sensitive lifecycle pass, the v4 shape-and-spec abstract
# interpretation, and the v5 held-lock concurrency pass) plus a
# bytecode-compile pass.
#
# The engine keeps a content-hash file cache (.graftcheck-cache.json,
# persisted across CI runs by actions/cache) so repeat runs only
# re-parse changed files; the CFG/dataflow passes run at parse time, so
# warm runs skip them entirely. Two runs execute here: the first is
# cold on a fresh checkout (or warm when CI restored the cache), the
# second is always warm. Both are held to a timing budget so the
# engine's cost stays visible in CI. Re-measured for v5 (concurrency
# pass included): cold 12.6s, warm 0.9s on the dev box class — the v5
# held-lock fixpoint (~1200 fns analyzed, ~18k held states) added
# ~4.4s cold over v4's 8.2s, so the cold budget is raised from v4's
# 15s to 20s to keep headroom on slower CI boxes; warm stays within
# the 3s budget. --stats prints all three passes' fixpoint counters
# (the concurrency line: classes with locks, guards inferred,
# held-lock states, helper re-runs) so analysis-cost regressions show
# up in CI logs:
#   run 1  < GRAFTCHECK_BUDGET_COLD_S  (default 20s)
#   run 2  < GRAFTCHECK_BUDGET_WARM_S  (default 3s, cache-served)
#
# Fast lane for local pre-push use:
#   scripts/lint.sh --diff [REF]      (default REF: origin/main)
# lints only files changed vs REF plus their reverse-dependency
# closure — a one-file change checks in well under a second warm.
# Usage: scripts/lint.sh [--diff [REF]] [extra graftcheck paths...]
set -euo pipefail
cd "$(dirname "$0")/.."

CACHE="${GRAFTCHECK_CACHE:-.graftcheck-cache.json}"

if [[ "${1:-}" == "--diff" ]]; then
    REF="${2:-origin/main}"
    echo "== graftcheck --diff ${REF} (fast lane) =="
    python -m ray_tpu.devtools.graftcheck \
        --cache "$CACHE" --stats --diff "$REF" \
        ray_tpu/ examples/ tests/
    echo "lint OK (diff lane)"
    exit 0
fi

echo "== graftcheck (whole-program engine) =="
python - "$CACHE" "$@" <<'PY'
import os
import sys
import time

from ray_tpu.devtools.graftcheck import main

cache, extra = sys.argv[1], sys.argv[2:]
args = ["--cache", cache, "--stats",
        "ray_tpu/", "examples/", "tests/", *extra]
budget_cold = float(os.environ.get("GRAFTCHECK_BUDGET_COLD_S", "20"))
budget_warm = float(os.environ.get("GRAFTCHECK_BUDGET_WARM_S", "3"))

t0 = time.monotonic()
rc = main(args)
cold = time.monotonic() - t0
if rc != 0:
    sys.exit(rc)

t0 = time.monotonic()
rc = main(args)
warm = time.monotonic() - t0
if rc != 0:
    sys.exit(rc)

print(f"graftcheck timing: run1 {cold:.2f}s (budget {budget_cold:.0f}s), "
      f"warm {warm:.2f}s (budget {budget_warm:.0f}s)")
if cold > budget_cold or warm > budget_warm:
    print("graftcheck: TIMING BUDGET EXCEEDED", file=sys.stderr)
    sys.exit(3)
PY

echo "== compileall =="
python -m compileall -q ray_tpu

echo "lint OK"
