#!/usr/bin/env python
"""CI smoke for compiled graphs (graftcheck-style live gate).

Spins up an in-process head plus one REAL remote node agent (a second
OS process over localhost TCP), compiles a 2-stage pipeline with one
stage on each node, pushes 100 executions through it under a trace, and
asserts the observability contract:

- results are correct for all 100 executions (shm edge head-side, RPC
  relay edges across the node boundary)
- stage prints are attributed to the ACTOR in `ray_tpu logs`
- per-stage SPAN events (cgraph:*) landed in the task-event stream with
  parent links (the timeline flow-arrow source)
- `ray_tpu_cgraph_*` metrics are present in a /metrics render
- teardown returns PlasmaStore channel accounting to zero

Exit 0 = healthy; any assertion prints the evidence and exits 1.
Run: python scripts/cgraph_smoke.py   (CI invokes it after logs_smoke)
"""
import contextlib
import io
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    import ray_tpu
    from ray_tpu.cgraph import InputNode
    from ray_tpu.cli import main as cli_main
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.util import metrics, tracing
    from ray_tpu.util.scheduling_strategies import (
        NodeAffinitySchedulingStrategy)

    c = Cluster(head_resources={"CPU": 2.0})
    try:
        rt = ray_tpu.get_runtime_context()  # noqa: F841 — init'd by Cluster
        remote = c.add_remote_node(num_cpus=2.0)
        pin = NodeAffinitySchedulingStrategy(node_id=remote.node_id,
                                             soft=False)

        @ray_tpu.remote
        class Stage:
            def __init__(self, k):
                self.k = k
                self.n = 0

            def add(self, x):
                self.n += 1
                if self.n % 25 == 0:
                    print(f"cgraph-smoke stage k={self.k} n={self.n}")
                return x + self.k

        a = Stage.remote(1)                                    # head node
        b = Stage.options(scheduling_strategy=pin).remote(10)  # remote

        with InputNode() as inp:
            dag = b.add.bind(a.add.bind(inp))
        compiled = dag.experimental_compile()

        with tracing.trace("cgraph-smoke") as span:
            for i in range(100):
                out = compiled.execute(i).get(timeout=60)
                assert out == i + 11, (i, out)
        print("100 executions OK")

        aid = a._actor_id.hex()
        time.sleep(2.0)  # let log batches + metric deltas land

        # 1) attributed logs: the resident loop's prints carry actor ids
        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            rc = cli_main(["logs", "--actor", aid[:12], "--limit", "500"])
        out = buf.getvalue()
        assert rc == 0, f"ray_tpu logs rc={rc}"
        lines = [ln for ln in out.splitlines() if "cgraph-smoke stage" in ln]
        assert len(lines) >= 3, \
            f"expected attributed stage lines for actor {aid[:12]}:\n{out}"
        print(f"log attribution OK ({len(lines)} lines)")

        # 2) per-stage spans in the task-event stream (timeline flow)
        spans = tracing.get_trace(span.trace_id)
        names = [s.get("name", "") for s in spans]
        cg = [n for n in names if n.startswith("cgraph:")]
        assert len(cg) >= 100, \
            f"expected >=100 cgraph:* spans, got {len(cg)}: {names[:10]}"
        pids = {s.get("pid") for s in spans if
                s.get("name", "").startswith("cgraph:")}
        assert len(pids) >= 2, f"spans from both stage processes: {pids}"
        print(f"timeline spans OK ({len(cg)} cgraph spans, "
              f"{len(pids)} processes)")

        # 3) cgraph metrics in the aggregated exposition
        body = metrics._render()
        for want in ("ray_tpu_cgraph_executions_total",
                     "ray_tpu_cgraph_roundtrip_seconds",
                     "ray_tpu_cgraph_node_exec_seconds"):
            assert want in body, f"missing {want} in /metrics"
        print("cgraph metrics OK")

        # 4) teardown releases every channel segment
        compiled.teardown()
        stats = c.runtime.nodes[c.runtime.head_node_id].store.stats()
        assert stats.get("num_channels", 0) == 0, stats
        print("teardown channel accounting OK")
        print("cgraph smoke OK")
        return 0
    finally:
        c.shutdown()


if __name__ == "__main__":
    sys.exit(main())
