#!/usr/bin/env python
"""CI smoke for the compiled-graph pipeline training engine (ISSUE 8).

Spins up an in-process head plus one REAL remote node agent (a second
OS process over localhost TCP), builds a 2-stage
`CompiledPipelineEngine` with stage 1 pinned to the remote node, and
drives 8 microbatches x 5 training steps through the 1F1B loop. Gates:

- the loss trajectory DECREASES (the engine is really training, not
  just moving bytes)
- per-stage SPAN events (cgraph:*) landed in the task-event stream
  from BOTH stage processes (the timeline flow-arrow source)
- `ray_tpu_pipeline_{step,stage_exec,bubble_wait}_seconds` are present
  in a /metrics render (stage rows ship on the throttled delta path)
- `engine.shutdown()` returns every store's channel accounting to the
  pre-engine baseline — zero leaked segments on either node
- a second engine with `wire_codec="int8"` (ISSUE 13,
  docs/COLLECTIVES.md) trains across the SAME head+remote split — the
  block-quantized activation/cotangent envelopes really cross the
  node boundary — with a decreasing loss,
  `ray_tpu_cgraph_channel_bytes_total{...codec="int8"}` visible in the
  /metrics render, and channel accounting clean after shutdown

Exit 0 = healthy; any assertion prints the evidence and exits 1.
Run: python scripts/pipeline_smoke.py   (CI invokes it after llm_smoke)
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _mlp(num_chunks: int, width: int, M: int, mb_size: int):
    import jax
    import jax.numpy as jnp

    k = jax.random.PRNGKey(0)

    def mk_mid():
        def fn(p, x):
            return jnp.tanh(x @ p["w"] + p["b"])
        return fn

    def mk_last():
        def fn(p, x, targets):
            return jnp.mean((x @ p["w"] + p["b"] - targets) ** 2)
        return fn

    fns = [mk_mid() for _ in range(num_chunks - 1)] + [mk_last()]
    params = [
        {"w": jax.random.normal(jax.random.fold_in(k, i),
                                (width, width)) * 0.3,
         "b": jnp.zeros((width,))}
        for i in range(num_chunks)]
    xs = jax.random.normal(jax.random.fold_in(k, 5), (M * mb_size, width))
    # a learnable fixed target map keeps the MSE trajectory cleanly
    # decreasing under sgd (random targets would flatten out fast)
    w_true = jax.random.normal(jax.random.fold_in(k, 6), (width, width)) * 0.5
    ys = jnp.tanh(xs @ w_true)
    mbs = [xs[i * mb_size:(i + 1) * mb_size] for i in range(M)]
    tgts = [ys[i * mb_size:(i + 1) * mb_size] for i in range(M)]
    return fns, params, mbs, tgts


def main() -> int:
    import optax

    import ray_tpu  # noqa: F401 — Cluster below owns init
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.train import CompiledPipelineEngine, PipelineConfig
    from ray_tpu.util import metrics, tracing
    from ray_tpu.util.scheduling_strategies import (
        NodeAffinitySchedulingStrategy)

    c = Cluster(head_resources={"CPU": 2.0})
    try:
        remote = c.add_remote_node(num_cpus=2.0)

        def store_channels() -> dict:
            return {nid: n.store.stats().get("num_channels", 0)
                    for nid, n in c.runtime.nodes.items()}

        baseline = store_channels()

        fns, params, mbs, tgts = _mlp(2, 16, M=8, mb_size=4)
        cfg = PipelineConfig(num_microbatches=8, channel_bytes=1 << 18)
        eng = CompiledPipelineEngine(
            fns, params, optax.sgd(0.05), **cfg.engine_kwargs(),
            scheduling_strategies=[
                NodeAffinitySchedulingStrategy(node_id=c.runtime.head_node_id,
                                               soft=False),
                NodeAffinitySchedulingStrategy(node_id=remote.node_id,
                                               soft=False)])
        losses = []
        with tracing.trace("pipeline-smoke") as span:
            for _ in range(5):
                losses.append(eng.step(mbs, tgts))
        print(f"5 steps OK, losses {[round(l, 5) for l in losses]}")

        # 1) training signal: every step strictly improves the loss
        assert all(b < a for a, b in zip(losses, losses[1:])), \
            f"loss did not decrease: {losses}"
        assert all(r["in_flight_residuals"] == 0 for r in eng.last_reports), \
            f"leaked fwd residuals: {eng.last_reports}"
        print("loss trajectory OK")

        # 2) per-stage spans from both stage processes
        time.sleep(2.0)  # let task-event batches land
        spans = tracing.get_trace(span.trace_id)
        cg = [s for s in spans if s.get("name", "").startswith("cgraph:")]
        pids = {s.get("pid") for s in cg}
        assert len(cg) >= 10, \
            f"expected >=10 cgraph:* stage spans, got {len(cg)}"
        assert len(pids) >= 2, \
            f"expected spans from both stage processes, pids={pids}"
        print(f"timeline spans OK ({len(cg)} spans, {len(pids)} processes)")

        # 3) pipeline metrics present (stage rows ride the throttled
        # worker delta path — poll briefly)
        deadline = time.monotonic() + 15
        want = ("ray_tpu_pipeline_step_seconds",
                "ray_tpu_pipeline_stage_exec_seconds",
                "ray_tpu_pipeline_bubble_wait_seconds")
        body = metrics._render()
        while (not all(w in body for w in want)
               and time.monotonic() < deadline):
            time.sleep(0.3)
            body = metrics._render()
        missing = [w for w in want if w not in body]
        assert not missing, f"missing metrics: {missing}"
        print("pipeline metrics OK")

        # 4) shutdown releases every channel segment on every node
        eng.shutdown()
        after = store_channels()
        assert after == baseline, \
            f"leaked channels: baseline={baseline} after={after}"
        print("shutdown channel accounting OK")

        # 5) wire-codec engine, live 2-node: stage 1 stays pinned to
        # the remote agent so the int8-quantized activation/cotangent
        # envelopes cross a REAL process/TCP boundary (RpcSender ->
        # QueueChannel reorder path), not just shm
        cfns, cparams, cmbs, ctgts = _mlp(2, 32, M=4, mb_size=32)
        ceng = CompiledPipelineEngine(
            cfns, cparams, optax.sgd(0.05),
            num_microbatches=4, wire_codec="int8",
            channel_bytes=1 << 18,
            scheduling_strategies=[
                NodeAffinitySchedulingStrategy(
                    node_id=c.runtime.head_node_id, soft=False),
                NodeAffinitySchedulingStrategy(
                    node_id=remote.node_id, soft=False)])
        closses = [ceng.step(cmbs, ctgts) for _ in range(4)]
        assert all(b < a for a, b in zip(closses, closses[1:])), \
            f"codec loss did not decrease: {closses}"
        deadline = time.monotonic() + 15
        body = metrics._render()
        while ('codec="int8"' not in body
               and time.monotonic() < deadline):
            time.sleep(0.3)
            body = metrics._render()
        int8_rows = [ln for ln in body.splitlines()
                     if ln.startswith("ray_tpu_cgraph_channel_bytes_total")
                     and 'codec="int8"' in ln]
        assert int8_rows, "no int8-tagged channel byte series scraped"
        ceng.shutdown()
        after = store_channels()
        assert after == baseline, \
            f"codec engine leaked channels: {baseline} -> {after}"
        print(f"wire-codec engine OK, losses "
              f"{[round(l, 5) for l in closses]}, "
              f"{len(int8_rows)} int8 byte series")
        print("pipeline smoke OK")
        return 0
    finally:
        c.shutdown()


if __name__ == "__main__":
    sys.exit(main())
