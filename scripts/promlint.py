#!/usr/bin/env python3
"""promlint — Prometheus text-format (version 0.0.4) validator.

graftcheck-style CI gate for the /metrics exposition: parses a scrape
body and reports structural errors instead of letting a malformed
exposition (bad label escaping, orphan TYPE lines, non-monotonic
histogram buckets) ship and silently break a real Prometheus scraper.

Checks
 - comment lines: well-formed `# HELP <name> ...` / `# TYPE <name> <kind>`
   with a known kind; at most one HELP and one TYPE per metric family;
   TYPE must precede the family's samples
 - sample lines: valid metric/label names, correctly escaped label
   values (`\\`, `\"`, `\n`), no duplicate label names, parseable value
 - family grouping: all samples of a family must be contiguous
 - histograms: `_bucket` needs an `le` label with a parseable bound,
   cumulative counts must be non-decreasing in `le` order, the `+Inf`
   bucket must exist and equal `_count` for the same label set
 - OpenMetrics exemplars (`... # {trace_id="..."} value ts`): allowed
   only on counter and `_bucket` samples, labels must parse with the
   same escaping rules, value/timestamp must parse, and a bucket
   exemplar's value must not exceed its finite `le` bound

Usage:
    promlint.py <file-or-url>     lint a saved body or live endpoint
    promlint.py --live            spin up an in-process ray_tpu cluster,
                                  run work, scrape, lint (the CI mode)
Exit code 0 = clean, 1 = findings (one per line on stderr).
"""
from __future__ import annotations

import math
import re
import sys
from typing import Dict, List, Optional, Tuple

_KINDS = {"counter", "gauge", "histogram", "summary", "untyped"}
_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")
_SUFFIXES = ("_bucket", "_sum", "_count")


def _family_of(sample_name: str, typed: Dict[str, str]) -> str:
    """Map a sample name to its family: histogram/summary samples carry
    a suffix on the declared family name."""
    for suf in _SUFFIXES:
        if sample_name.endswith(suf):
            base = sample_name[: -len(suf)]
            if typed.get(base) in ("histogram", "summary"):
                return base
    return sample_name


def _parse_labels(raw: str) -> Tuple[Optional[List[Tuple[str, str]]], str]:
    """Parse `k="v",k2="v2"` with escape validation; returns
    (pairs, error). A None pairs means unparseable."""
    pairs: List[Tuple[str, str]] = []
    i, n = 0, len(raw)
    while i < n:
        j = raw.find("=", i)
        if j < 0:
            return None, f"missing '=' in labels at {raw[i:]!r}"
        name = raw[i:j].strip()
        if not _LABEL_RE.match(name):
            return None, f"bad label name {name!r}"
        if j + 1 >= n or raw[j + 1] != '"':
            return None, f"label {name!r}: value not quoted"
        k = j + 2
        val = []
        closed = False
        while k < n:
            c = raw[k]
            if c == "\\":
                if k + 1 >= n or raw[k + 1] not in ('\\', '"', 'n'):
                    return None, (f"label {name!r}: invalid escape "
                                  f"\\{raw[k + 1] if k + 1 < n else ''}")
                val.append({"\\": "\\", '"': '"', "n": "\n"}[raw[k + 1]])
                k += 2
            elif c == '"':
                closed = True
                k += 1
                break
            elif c == "\n":
                return None, f"label {name!r}: raw newline in value"
            else:
                val.append(c)
                k += 1
        if not closed:
            return None, f"label {name!r}: unterminated value"
        pairs.append((name, "".join(val)))
        if k < n:
            if raw[k] != ",":
                return None, f"junk after label {name!r}: {raw[k:]!r}"
            k += 1
        i = k
    return pairs, ""


def _parse_value(s: str) -> Optional[float]:
    try:
        return float(s)
    except ValueError:
        if s in ("+Inf", "-Inf", "NaN"):
            return {"+Inf": math.inf, "-Inf": -math.inf,
                    "NaN": math.nan}[s]
        return None


def lint(body: str) -> List[str]:
    errors: List[str] = []
    helped: Dict[str, int] = {}
    typed: Dict[str, str] = {}
    closed_families: set = set()
    current_family: Optional[str] = None
    # histogram accumulation: (family, frozenset(non-le labels)) ->
    # [(le, value)], and _count values for the +Inf cross-check
    buckets: Dict[tuple, List[Tuple[float, float]]] = {}
    counts: Dict[tuple, float] = {}

    for lineno, line in enumerate(body.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) < 2 or parts[1] not in ("HELP", "TYPE"):
                continue  # free-form comment: legal
            if len(parts) < 3 or not _NAME_RE.match(parts[2]):
                errors.append(f"line {lineno}: malformed {parts[1]} line")
                continue
            name = parts[2]
            if parts[1] == "HELP":
                if name in helped:
                    errors.append(
                        f"line {lineno}: duplicate HELP for {name}")
                helped[name] = lineno
            else:
                kind = parts[3].strip() if len(parts) > 3 else ""
                if kind not in _KINDS:
                    errors.append(
                        f"line {lineno}: TYPE {name}: unknown kind "
                        f"{kind!r}")
                if name in typed:
                    errors.append(
                        f"line {lineno}: duplicate TYPE for {name}")
                if name in closed_families or name == current_family:
                    errors.append(
                        f"line {lineno}: TYPE for {name} appears after "
                        f"its samples")
                typed.setdefault(name, kind)
            continue
        # an OpenMetrics exemplar rides after ` # ` on the sample line;
        # split it off before the classic-format sample parse
        exemplar = None
        if " # " in line:
            line, _, exraw = line.partition(" # ")
            exemplar = exraw.strip()
        # sample line: name[{labels}] value [timestamp]
        m = re.match(r"^([a-zA-Z_:][a-zA-Z0-9_:]*)"
                     r"(?:\{(.*)\})?\s+(\S+)(?:\s+(-?\d+))?$", line)
        if m is None:
            errors.append(f"line {lineno}: unparseable sample: {line!r}")
            continue
        sname, rawlabels, rawval = m.group(1), m.group(2), m.group(3)
        fam = _family_of(sname, typed)
        if fam != current_family:
            if current_family is not None:
                closed_families.add(current_family)
            if fam in closed_families:
                errors.append(
                    f"line {lineno}: samples of {fam} are not contiguous")
            current_family = fam
        labels: List[Tuple[str, str]] = []
        if rawlabels:
            parsed, err = _parse_labels(rawlabels)
            if parsed is None:
                errors.append(f"line {lineno}: {sname}: {err}")
                continue
            labels = parsed
            names = [k for k, _ in labels]
            if len(names) != len(set(names)):
                errors.append(
                    f"line {lineno}: {sname}: duplicate label name")
        value = _parse_value(rawval)
        if value is None:
            errors.append(
                f"line {lineno}: {sname}: unparseable value {rawval!r}")
            continue
        if exemplar is not None:
            exm = re.match(r"^\{(.*)\}\s+(\S+)(?:\s+(\S+))?$", exemplar)
            if exm is None:
                errors.append(
                    f"line {lineno}: {sname}: malformed exemplar "
                    f"{exemplar!r}")
            else:
                if typed.get(fam) == "histogram" \
                        and not sname.endswith("_bucket"):
                    errors.append(
                        f"line {lineno}: {sname}: exemplar on a "
                        f"histogram sample that is not _bucket")
                elif typed.get(fam) not in ("histogram", "counter"):
                    errors.append(
                        f"line {lineno}: {sname}: exemplar on a "
                        f"{typed.get(fam) or 'untyped'} family")
                if exm.group(1):
                    expairs, exerr = _parse_labels(exm.group(1))
                    if expairs is None:
                        errors.append(
                            f"line {lineno}: {sname}: exemplar: {exerr}")
                exval = _parse_value(exm.group(2))
                if exval is None:
                    errors.append(
                        f"line {lineno}: {sname}: unparseable exemplar "
                        f"value {exm.group(2)!r}")
                if exm.group(3) is not None \
                        and _parse_value(exm.group(3)) is None:
                    errors.append(
                        f"line {lineno}: {sname}: unparseable exemplar "
                        f"timestamp {exm.group(3)!r}")
                if exval is not None and sname.endswith("_bucket"):
                    le = dict(labels).get("le")
                    bound = _parse_value(le) if le is not None else None
                    if bound is not None and not math.isinf(bound) \
                            and exval > bound:
                        errors.append(
                            f"line {lineno}: {sname}: exemplar value "
                            f"{exval} exceeds its le={le} bound")
        if typed.get(fam) == "histogram":
            others = frozenset((k, v) for k, v in labels if k != "le")
            if sname.endswith("_bucket"):
                le = dict(labels).get("le")
                bound = _parse_value(le) if le is not None else None
                if bound is None:
                    errors.append(
                        f"line {lineno}: {sname}: _bucket needs a "
                        f"parseable le label, got {le!r}")
                else:
                    buckets.setdefault((fam, others), []).append(
                        (bound, value))
            elif sname.endswith("_count"):
                counts[(fam, others)] = value

    for (fam, others), rows in buckets.items():
        tag = dict(others)
        rows = sorted(rows, key=lambda r: r[0])
        bounds = [b for b, _ in rows]
        if not any(math.isinf(b) for b in bounds):
            errors.append(f"{fam}{tag}: histogram has no +Inf bucket")
        if len(bounds) != len(set(bounds)):
            errors.append(f"{fam}{tag}: duplicate le bound")
        prev = -math.inf
        for b, v in rows:
            if v < prev:
                errors.append(
                    f"{fam}{tag}: bucket le={b} count {v} < previous "
                    f"{prev} (not cumulative)")
            prev = v
        cnt = counts.get((fam, others))
        inf_rows = [v for b, v in rows if math.isinf(b) and b > 0]
        if cnt is not None and inf_rows and inf_rows[0] != cnt:
            errors.append(
                f"{fam}{tag}: +Inf bucket {inf_rows[0]} != _count {cnt}")
    return errors


def _fetch(target: str) -> str:
    if target.startswith(("http://", "https://")):
        import urllib.request

        with urllib.request.urlopen(target, timeout=10) as r:
            return r.read().decode()
    with open(target) as f:
        return f.read()


def _live_scrape() -> str:
    """CI mode: stand up an in-process cluster, generate traffic across
    the instrumented paths (tasks, puts/gets, a worker-side user
    metric), then scrape the real /metrics server."""
    import time
    import urllib.request

    import ray_tpu
    from ray_tpu.util import metrics as metrics_mod

    ray_tpu.init(num_cpus=2)
    try:
        @ray_tpu.remote
        def work(x):
            from ray_tpu.util.metrics import Counter

            Counter("promlint_worker_events_total", "live-lint probe",
                    tag_keys=("k",)).inc(tags={"k": 'q"uote\\slash'})
            return x * 2

        # exemplar-bearing histogram on the head: the scrape must carry
        # a `# {trace_id="..."} value ts` suffix promlint can parse
        metrics_mod.Histogram(
            "promlint_probe_seconds", "live-lint exemplar probe",
            boundaries=[0.1, 1.0]).observe(0.05, exemplar="ab" * 16)
        ref = ray_tpu.put(b"x" * 200_000)  # exercise the store path
        assert ray_tpu.get([work.remote(i) for i in range(8)],
                           timeout=120) == [2 * i for i in range(8)]
        assert len(ray_tpu.get(ref, timeout=60)) == 200_000
        host, port = metrics_mod.start_metrics_server()
        deadline = time.time() + 20
        body = ""
        while time.time() < deadline:  # wait for the worker delta ship
            with urllib.request.urlopen(
                    f"http://{host}:{port}/metrics", timeout=10) as r:
                body = r.read().decode()
            if "promlint_worker_events_total" in body:
                break
            time.sleep(0.5)
        return body
    finally:
        ray_tpu.shutdown()


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv:
        print(__doc__, file=sys.stderr)
        return 2
    if argv[0] == "--live":
        sys.path.insert(0, ".")
        body = _live_scrape()
        if "promlint_worker_events_total" not in body:
            print("promlint --live: worker metric never reached the head "
                  "scrape", file=sys.stderr)
            return 1
        if '# {trace_id="' not in body:
            print("promlint --live: exemplar never appeared in the head "
                  "scrape", file=sys.stderr)
            return 1
    else:
        body = _fetch(argv[0])
    errors = lint(body)
    for e in errors:
        print(e, file=sys.stderr)
    print(f"promlint: {len(body.splitlines())} lines, "
          f"{len(errors)} error(s)")
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
