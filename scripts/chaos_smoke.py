#!/usr/bin/env python
"""CI smoke for the chaos engine + survivable hot paths (ISSUE 10).

Spins up an in-process head plus one REAL remote node agent (second OS
process over localhost TCP) and gates the three recovery stories on live
clusters:

1. **Heartbeat-miss accounting**: SIGSTOP the agent briefly (below the
   configured miss threshold) — `ray_tpu_heartbeat_misses_total` counts
   the silent periods, and the node is NOT fenced.
2. **Pipeline engine kill + recover**: a seeded ChaosPlan kills stage
   1's actor mid-training; `step()` fails typed, `engine.recover()`
   respawns/reallocates/restores, and the post-recovery loss trajectory
   is BIT-IDENTICAL to a clean restart from the same checkpoint.
3. **LLM replica failover**: concurrent clients stream from a
   2-replica LLMServer through `resilient_stream`; the replica serving
   them is killed mid-stream; every client still receives its COMPLETE,
   prefix-consistent greedy token sequence (checked against a
   driver-local ground-truth engine) — zero errors, zero duplicated or
   lost tokens.

Exit 0 = healthy; any assertion prints the evidence and exits 1.
Run: python scripts/chaos_smoke.py   (CI invokes it after pipeline_smoke)
"""
import os
import signal
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _mlp(num_chunks: int, width: int, M: int, mb_size: int):
    import jax
    import jax.numpy as jnp

    k = jax.random.PRNGKey(0)

    def mk_mid():
        def fn(p, x):
            return jnp.tanh(x @ p["w"] + p["b"])
        return fn

    def mk_last():
        def fn(p, x, targets):
            return jnp.mean((x @ p["w"] + p["b"] - targets) ** 2)
        return fn

    fns = [mk_mid() for _ in range(num_chunks - 1)] + [mk_last()]
    params = [
        {"w": jax.random.normal(jax.random.fold_in(k, i),
                                (width, width)) * 0.3,
         "b": jnp.zeros((width,))}
        for i in range(num_chunks)]
    xs = jax.random.normal(jax.random.fold_in(k, 5), (M * mb_size, width))
    w_true = jax.random.normal(jax.random.fold_in(k, 6),
                               (width, width)) * 0.5
    ys = jnp.tanh(xs @ w_true)
    mbs = [xs[i * mb_size:(i + 1) * mb_size] for i in range(M)]
    tgts = [ys[i * mb_size:(i + 1) * mb_size] for i in range(M)]
    return fns, params, mbs, tgts


def _part_heartbeat(c, remote) -> None:
    from ray_tpu.util import metrics

    proc = remote._agent_proc
    os.kill(proc.pid, signal.SIGSTOP)
    try:
        time.sleep(1.6)  # several silent periods, below the fence bar
    finally:
        os.kill(proc.pid, signal.SIGCONT)
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        if "ray_tpu_heartbeat_misses_total" in metrics._render():
            break
        time.sleep(0.2)
    body = metrics._render()
    assert "ray_tpu_heartbeat_misses_total" in body, \
        "no heartbeat misses counted during the SIGSTOP window"
    info = next(n for n in c.runtime.gcs.nodes()
                if n.node_id == remote.node_id)
    assert info.alive, \
        "node fenced although misses stayed below the threshold"
    print("heartbeat-miss accounting OK (counted, not fenced)")


def _part_pipeline(c, remote, ckpt_dir: str) -> None:
    import optax

    from ray_tpu import chaos
    from ray_tpu.exceptions import (CompiledGraphClosedError,
                                    CompiledGraphError)
    from ray_tpu.train import CompiledPipelineEngine, PipelineConfig
    from ray_tpu.util import metrics
    from ray_tpu.util.scheduling_strategies import (
        NodeAffinitySchedulingStrategy)

    fns, params, mbs, tgts = _mlp(2, 16, M=8, mb_size=4)
    cfg = PipelineConfig(num_microbatches=8, channel_bytes=1 << 18,
                         checkpoint_dir=ckpt_dir, checkpoint_every=2)
    tx = optax.sgd(0.05)
    eng = CompiledPipelineEngine(
        fns, params, tx, **cfg.engine_kwargs(),
        scheduling_strategies=[
            NodeAffinitySchedulingStrategy(node_id=c.runtime.head_node_id,
                                           soft=False),
            NodeAffinitySchedulingStrategy(node_id=remote.node_id,
                                           soft=False)])

    # seeded kill schedule: stage 1's actor (the REMOTE stage) dies at
    # t=1.2s while steps are flowing — replayable via the plan seed
    victim_id = eng.actors[1]._actor_id

    def kill_stage(rt, aid=victim_id):
        rt.kill_actor(aid, no_restart=True)

    plan = chaos.ChaosPlan(seed=42,
                           kills=(chaos.KillSpec(at_s=1.2,
                                                 target=kill_stage),))
    engine = chaos.enable(plan, runtime=c.runtime)

    losses = []
    failed_at = None
    for step_i in range(60):
        try:
            losses.append(eng.step(mbs, tgts, timeout=60))
        except (CompiledGraphClosedError, CompiledGraphError) as e:
            failed_at = step_i
            print(f"stage kill surfaced at step {step_i}: "
                  f"{type(e).__name__}")
            break
    assert failed_at is not None, "chaos kill never landed in 60 steps"
    assert engine.injected.get("kill") == 1, engine.injected
    chaos.disable()

    ck = CompiledPipelineEngine.latest_checkpoint(ckpt_dir)
    assert ck is not None, "no committed checkpoint at kill time"
    resumed_from = eng.recover()
    print(f"recovered from {os.path.basename(ck)} (step {resumed_from})")
    resumed = [eng.step(mbs, tgts, timeout=60) for _ in range(3)]
    eng.shutdown()

    # clean restart from the SAME checkpoint must replay bit-identically
    fresh = CompiledPipelineEngine(
        fns, params, tx, **PipelineConfig(
            num_microbatches=8, channel_bytes=1 << 18).engine_kwargs(),
        scheduling_strategies=[
            NodeAffinitySchedulingStrategy(node_id=c.runtime.head_node_id,
                                           soft=False),
            NodeAffinitySchedulingStrategy(node_id=remote.node_id,
                                           soft=False)])
    try:
        assert fresh.restore(ck) == resumed_from
        replay = [fresh.step(mbs, tgts, timeout=60) for _ in range(3)]
    finally:
        fresh.shutdown()
    assert resumed == replay, \
        f"post-recovery trajectory diverged: {resumed} vs {replay}"
    body = metrics._render()
    assert "ray_tpu_chaos_injected_total" in body, \
        "chaos injection counter missing from /metrics"
    print(f"pipeline recover OK: resumed {resumed} == replay (bitwise)")


def _part_llm_failover() -> None:
    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.serve.llm import (EngineConfig, LLMEngine, LLMServer,
                                   build_model, resilient_stream)

    n_clients, max_tokens = 4, 40
    prompts = [[2, 5, 9], [1, 1, 4], [7, 3], [4, 8, 6, 2]]

    # driver-local ground truth: same model family + seed as every
    # replica, so greedy decode defines THE correct stream per prompt
    model, params = build_model("gpt-tiny", seed=0)
    ref = LLMEngine(model, params, EngineConfig(max_batch=4,
                                                num_blocks=64),
                    name="truth")
    truth = []
    streams = [ref.add_request(p, max_tokens=max_tokens, eos_id=None)
               for p in prompts]
    ref.run_until_idle(timeout=300)
    truth = [s.tokens(timeout=60) for s in streams]
    print("ground truth computed")

    app = serve.deployment(
        num_replicas=2, health_check_period_s=0.5,
        health_check_timeout_s=2.0)(LLMServer).bind(
        model="gpt-tiny",
        engine_config={"max_batch": 4, "num_blocks": 64})
    h = serve.run(app)
    # wait for both replicas (each compiles the model on first request)
    deadline = time.monotonic() + 240
    while serve.status()["LLMServer"]["running"] != 2:
        assert time.monotonic() < deadline, "replicas never came up"
        time.sleep(0.5)
    print("2 replicas up")

    got = [[] for _ in range(n_clients)]
    errs = [None] * n_clients
    gens = [resilient_stream(h, {"tokens": prompts[i],
                                 "max_tokens": max_tokens,
                                 "eos_id": None})
            for i in range(n_clients)]
    kill_state = {"done": False}
    lock = threading.Lock()

    def client(i):
        try:
            for tok in gens[i]:
                got[i].append(tok)
                with lock:
                    due = (not kill_state["done"]
                           and sum(len(g) for g in got) >= 12)
                    if due:
                        kill_state["done"] = True
                if due:
                    aid = gens[i].replica_actor_id
                    controller = ray_tpu.get_actor("SERVE_CONTROLLER")
                    _, _, reps = ray_tpu.get(
                        controller.get_replicas.remote("LLMServer"),
                        timeout=30)
                    victim = next((r for r in reps
                                   if r._actor_id == aid), None)
                    if victim is not None:
                        print(f"client {i} killing its replica "
                              f"{aid.hex()[:8]} mid-stream")
                        ray_tpu.kill(victim)
        except BaseException as e:  # noqa: BLE001 — asserted below
            errs[i] = e

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(n_clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=600)
    assert not any(t.is_alive() for t in threads), "a client hung"
    assert not any(errs), f"client errors: {errs}"
    failovers = sum(g.failovers for g in gens)
    assert failovers >= 1, "the kill never forced a failover"
    for i in range(n_clients):
        assert got[i] == truth[i], (
            f"client {i} stream corrupted/lost tokens:\n"
            f"  got  {got[i]}\n  want {truth[i]}")
    print(f"LLM failover OK: {n_clients} streams complete + "
          f"prefix-consistent through {failovers} failover(s)")
    serve.shutdown()


def main() -> int:
    import tempfile

    import ray_tpu  # noqa: F401 — Cluster below owns init
    from ray_tpu.cluster_utils import Cluster

    c = Cluster(head_resources={"CPU": 4.0},
                system_config={"health_check_period_s": 0.3,
                               "health_check_timeout_s": 8.0,
                               "heartbeat_miss_threshold": 25})
    try:
        remote = c.add_remote_node(num_cpus=2.0)
        _part_heartbeat(c, remote)
        with tempfile.TemporaryDirectory() as d:
            _part_pipeline(c, remote, d)
        _part_llm_failover()
        print("chaos smoke OK")
        return 0
    finally:
        from ray_tpu import chaos

        chaos.disable()
        c.shutdown()


if __name__ == "__main__":
    sys.exit(main())
