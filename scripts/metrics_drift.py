#!/usr/bin/env python
"""metrics_drift: keep docs/OBSERVABILITY.md and the emitted metric
families from drifting apart (they co-evolved by hand for 15 PRs).

Two directions, both fatal:

- code -> doc: every ``ray_tpu_*`` family constructed in ``ray_tpu/``
  (AST scan for ``Counter``/``Gauge``/``Histogram`` calls with a string
  first argument, plus the scrape-time ``fams.get(name, kind, help)``
  families in util/metrics.py — NOT a text grep, which would
  false-positive on strings like the ``ray_tpu_postmortem`` bundle-dir
  name) must be named somewhere in docs/OBSERVABILITY.md.
- doc -> code: every ``ray_tpu_*`` series the doc names must be
  constructed somewhere in ``ray_tpu/``. PromQL spellings
  (``_bucket``/``_sum``/``_count`` on a histogram) and the doc's
  shorthand continuation cells (``ray_tpu_object_store_bytes_used`` /
  ``_capacity_bytes`` / ``_objects``) are normalised first.

Run: ``python scripts/metrics_drift.py`` (exit 1 on drift).
"""
import ast
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DOC = os.path.join(REPO, "docs", "OBSERVABILITY.md")
PKG = os.path.join(REPO, "ray_tpu")

METRIC_CTORS = ("Counter", "Gauge", "Histogram")


def code_series():
    """{family_name: 'path:line'} for every metric constructed in
    ray_tpu/ — AST only, so arbitrary ray_tpu_* strings don't count."""
    out = {}
    for root, _dirs, files in os.walk(PKG):
        for fn in files:
            if not fn.endswith(".py"):
                continue
            path = os.path.join(root, fn)
            with open(path, encoding="utf-8") as f:
                try:
                    tree = ast.parse(f.read(), filename=path)
                except SyntaxError:
                    continue
            for node in ast.walk(tree):
                if not isinstance(node, ast.Call) or not node.args:
                    continue
                fname = (node.func.id if isinstance(node.func, ast.Name)
                         else node.func.attr
                         if isinstance(node.func, ast.Attribute) else "")
                if not fname.endswith(METRIC_CTORS):
                    # scrape-time families: fams.get(name, kind, help)
                    # where kind is a literal gauge/counter/histogram
                    if not (fname == "get" and len(node.args) >= 2
                            and isinstance(node.args[1], ast.Constant)
                            and node.args[1].value in
                            ("gauge", "counter", "histogram")):
                        continue
                arg = node.args[0]
                if (isinstance(arg, ast.Constant)
                        and isinstance(arg.value, str)
                        and arg.value.startswith("ray_tpu_")):
                    rel = os.path.relpath(path, REPO)
                    out.setdefault(arg.value, f"{rel}:{node.lineno}")
    return out


def doc_series(code):
    """Set of normalised ray_tpu_* names the doc refers to."""
    with open(DOC, encoding="utf-8") as f:
        text = f.read()
    names = set()
    for line in text.splitlines():
        # OpenMetrics exemplar recipes (`... # {trace_id="..."} value
        # ts`) are sample syntax, not series references — strip them so
        # nothing inside an exemplar can register as a doc-named series
        line = re.sub(r"#\s*\{[^}]*\}[^`]*", "", line)
        # brace alternation: ray_tpu_serve_slo_{ok,violated}_total (the
        # prefix ends with "_"); otherwise the braces are a tag list on
        # a complete series name, e.g. ..._memory_bytes{device,kind}
        for pre, alts, post in re.findall(
                r"(ray_tpu_[a-z0-9_]*)\{([a-z0-9_,]+)\}([a-z0-9_]*)",
                line):
            if pre.endswith("_"):
                names.update(f"{pre}{a}{post}" for a in alts.split(","))
            else:
                names.add(pre)
        line = re.sub(r"ray_tpu_[a-z0-9_]*\{[a-z0-9_,]+\}[a-z0-9_]*",
                      "", line)
        full = re.findall(r"ray_tpu_[a-z0-9_]*[a-z0-9]", line)
        names.update(full)
        # shorthand continuation cells: `_capacity_bytes` on a line that
        # already named a full series — resolve against every underscore
        # prefix of the line's full names, keep matches that exist
        for short in re.findall(r"`(_[a-z0-9_]*[a-z0-9])`", line):
            for f_name in full:
                parts = f_name.split("_")
                for i in range(len(parts), 1, -1):
                    cand = "_".join(parts[:i]) + short
                    if cand in code:
                        names.add(cand)
                        break
    # promql spellings of histogram families; family-prefix mentions
    # (e.g. the `ray_tpu_postmortem` bundle dir, "the ray_tpu_llm
    # family") are not series references and are dropped
    norm = set()
    for n in names:
        if n not in code:
            for suf in ("_bucket", "_sum", "_count"):
                if n.endswith(suf) and n[:-len(suf)] in code:
                    n = n[:-len(suf)]
                    break
        if n not in code and any(c.startswith(n + "_") for c in code):
            continue
        norm.add(n)
    return norm


def main() -> int:
    code = code_series()
    doc = doc_series(code)
    undocumented = sorted(set(code) - doc)
    unemitted = sorted(doc - set(code))
    ok = True
    if undocumented:
        ok = False
        print("metrics_drift: emitted but not in docs/OBSERVABILITY.md:")
        for n in undocumented:
            print(f"  {n}  ({code[n]})")
    if unemitted:
        ok = False
        print("metrics_drift: named in docs/OBSERVABILITY.md but never "
              "constructed in ray_tpu/:")
        for n in unemitted:
            print(f"  {n}")
    if ok:
        print(f"metrics_drift: OK — {len(code)} families, all documented, "
              f"no stale doc rows")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
