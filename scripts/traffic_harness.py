#!/usr/bin/env python
"""Production-shaped LLM traffic harness (ROADMAP item 3 /
docs/LLM_SERVE.md "Prefix caching & sessions").

Every serving bench so far drove FIXED synthetic concurrency; real chat
traffic is nothing like that. This harness generates and replays
SESSION traces with the three properties that dominate production load,
through the REAL serve stack (controller, session-aware router, HTTP
proxy, streaming):

- **Bursty arrivals** — a Poisson-burst process: exponential gaps
  between burst epochs, geometric burst sizes, so concurrency spikes
  and idles instead of holding a constant.
- **Heavy-tailed sessions** — turn counts drawn from a bounded Zipf:
  most conversations are one or two turns, a heavy tail runs long.
- **Shared-prefix mix** — a configurable fraction of sessions opens
  with one of a few long common system prompts; every later turn
  re-sends the full conversation so far (context + the model's own
  completion + fresh user tokens), the exact shape the radix prefix
  cache and session affinity are built to exploit.

Reported: goodput (completed streams/s), p50/p99 TTFT and TPOT,
failure/failover/preemption counts, and the scrape-level prefix-cache
hit rate. Runs under ``RAY_TPU_CHAOS`` (use ``--transport handle`` so
streams ride ``resilient_stream`` failover) — the scale story composes
with the fault story.

    python scripts/traffic_harness.py --sessions 40 --replicas 2
    python scripts/traffic_harness.py --transport handle \
        --chaos "seed=7;kill=replica:LLMServer@4" --json /tmp/row.json

Library use: ``make_trace`` / ``replay`` / ``summarize`` are imported
by scripts/traffic_smoke.py (the CI gate) and bench.py (the
``traffic_*`` rows).
"""
from __future__ import annotations

import argparse
import json
import math
import os
import random
import sys
import threading
import time
import urllib.request
from typing import Any, Dict, List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# engine shape the harness deploys (smoke-sized; bench overrides)
ENGINE_CFG = dict(block_size=8, num_blocks=256, max_batch=8,
                  max_blocks_per_seq=16, prefill_buckets=(16, 32, 64, 128),
                  max_prefill_tokens_per_step=128, prefix_cache=True)


# ---------------------------------------------------------------------------
# trace generation


def _zipf_turns(rng: random.Random, max_turns: int, a: float = 2.0) -> int:
    """Bounded Zipf sample on [1, max_turns]: P(k) ∝ 1/k^a."""
    weights = [1.0 / (k ** a) for k in range(1, max_turns + 1)]
    return rng.choices(range(1, max_turns + 1), weights=weights)[0]


def make_trace(n_sessions: int, seed: int = 0, *, shared_frac: float = 0.6,
               n_prefixes: int = 2, prefix_len: int = 24,
               user_len: int = 4, max_turns: int = 3, max_tokens: int = 6,
               burst_gap_s: float = 0.4, burst_size_p: float = 0.35,
               vocab: int = 500) -> Dict[str, Any]:
    """Deterministic session trace. Each session: an arrival time (from
    the Poisson-burst process), a Zipf turn count, an opening prefix
    (one of ``n_prefixes`` shared system prompts for a ``shared_frac``
    slice of sessions, unique tokens otherwise), and per-turn fresh user
    token chunks. Completions are NOT in the trace — they come from the
    model at replay time (and, being greedy, are reproducible by a
    reference engine)."""
    rng = random.Random(seed)
    prefixes = [[rng.randrange(1, vocab) for _ in range(prefix_len)]
                for _ in range(n_prefixes)]
    sessions = []
    t = 0.0
    remaining = n_sessions
    while remaining > 0:
        t += rng.expovariate(1.0 / burst_gap_s)   # burst epoch
        size = 1
        while rng.random() > burst_size_p and size < remaining:
            size += 1                             # geometric burst size
        for _ in range(min(size, remaining)):
            sid = f"s{n_sessions - remaining:03d}"
            remaining -= 1
            shared = rng.random() < shared_frac
            prefix = (rng.choice(prefixes) if shared else
                      [rng.randrange(1, vocab) for _ in range(prefix_len)])
            turns = _zipf_turns(rng, max_turns)
            sessions.append({
                "sid": sid,
                "arrival_s": round(t + rng.uniform(0.0, 0.05), 4),
                "shared": shared,
                "prefix": list(prefix),
                "chunks": [[rng.randrange(1, vocab)
                            for _ in range(user_len)]
                           for _ in range(turns)],
                "max_tokens": max_tokens,
            })
    return {"seed": seed, "shared_frac": shared_frac,
            "prefix_len": prefix_len, "sessions": sessions}


def reference_completions(trace: Dict[str, Any], model: str = "gpt-tiny",
                          engine_cfg: Optional[dict] = None
                          ) -> Dict[str, List[List[int]]]:
    """Cache-OFF ground truth: a driver-local engine replays every
    session sequentially (greedy, unshared) — the token streams any
    cache/routing configuration must reproduce exactly."""
    from ray_tpu.serve.llm import EngineConfig, LLMEngine, build_model

    cfg = dict(engine_cfg or ENGINE_CFG)
    cfg["prefix_cache"] = False
    m, params = build_model(model)
    eng = LLMEngine(m, params, EngineConfig(**cfg))
    out: Dict[str, List[List[int]]] = {}
    for s in trace["sessions"]:
        ctx = list(s["prefix"])
        outs = []
        for chunk in s["chunks"]:
            ctx = ctx + chunk
            st = eng.add_request(ctx, max_tokens=s["max_tokens"])
            eng.run_until_idle(timeout=600)
            toks = st.tokens()
            outs.append(toks)
            ctx = ctx + toks
        out[s["sid"]] = outs
    eng.pool.check_leaks()
    return out


# ---------------------------------------------------------------------------
# replay


def _stream_http(base_url: str, deployment: str, sid: str,
                 payload: dict, timeout: float) -> tuple:
    """One streamed turn over the real HTTP proxy (NDJSON framing).
    Returns (tokens, ttft_s, tpot_list_s)."""
    url = f"{base_url}/{deployment}?stream=1&session={sid}"
    body = json.dumps({**payload, "stream": True}).encode()
    headers = {"Content-Type": "application/json"}
    try:  # propagate an active trace like a W3C-instrumented client
        from ray_tpu.util import tracing as _trc

        tctx = _trc.current_context()
        if tctx:
            headers["traceparent"] = _trc.format_traceparent(tctx)
    except Exception:  # noqa: BLE001 — tracing must never fail traffic
        pass
    req = urllib.request.Request(url, body, headers)
    toks: List[int] = []
    tpots: List[float] = []
    t0 = time.perf_counter()
    ttft = None
    with urllib.request.urlopen(req, timeout=timeout) as r:
        last = t0
        for line in r:
            line = line.strip()
            if not line:
                continue
            now = time.perf_counter()
            if ttft is None:
                ttft = now - t0
            else:
                tpots.append(now - last)
            last = now
            toks.append(int(json.loads(line)))
    return toks, (ttft if ttft is not None else time.perf_counter() - t0), \
        tpots


def _stream_handle(handle, sid: str, payload: dict, timeout: float,
                   resilient: bool) -> tuple:
    """One streamed turn through the routing handle — with
    ``resilient`` the stream rides FailoverResponseGenerator and
    survives replica kills (the chaos-mode transport). Returns
    (tokens, ttft_s, tpots, failovers)."""
    from ray_tpu.serve.llm import resilient_stream

    if resilient:
        gen = resilient_stream(handle, payload, session_id=sid)
    else:
        gen = handle.options(stream=True, session_id=sid).remote(
            {**payload, "stream": True})
    toks: List[int] = []
    tpots: List[float] = []
    t0 = time.perf_counter()
    ttft = None
    last = t0
    deadline = t0 + timeout
    while True:
        try:
            tok = gen.next(timeout=max(1.0, deadline - time.perf_counter()))
        except StopIteration:
            break
        now = time.perf_counter()
        if ttft is None:
            ttft = now - t0
        else:
            tpots.append(now - last)
        last = now
        toks.append(int(tok))
    return toks, (ttft if ttft is not None else time.perf_counter() - t0), \
        tpots, getattr(gen, "failovers", 0)


def replay(trace: Dict[str, Any], *, base_url: Optional[str] = None,
           handle=None, deployment: str = "LLMServer",
           transport: str = "http", timeout: float = 240.0,
           time_scale: float = 1.0, tracing: bool = False) -> Dict[str, Any]:
    """Replay the trace against a live deployment: one thread per
    session (spawned at its arrival time), turns sequential within a
    session, the full conversation re-sent each turn. Returns
    {"records": [...], "wall_s": float} — one record per request with
    tokens/ttft/tpots/ok/failovers for summarize().

    ``tracing`` opens a driver-rooted distributed-trace span around
    every turn (W3C-width trace id): the http transport forwards it as
    a ``traceparent`` header, the handle transports ride the routing
    handle's context capture — so each turn becomes ONE stored trace
    spanning client, proxy/router, replica, and engine."""
    records: List[dict] = []
    rec_lock = threading.Lock()
    t0 = time.perf_counter()

    def run_session(s):
        ctx = list(s["prefix"])
        for turn, chunk in enumerate(s["chunks"]):
            ctx = ctx + chunk
            payload = {"tokens": ctx, "max_tokens": s["max_tokens"]}
            rec = {"sid": s["sid"], "turn": turn, "shared": s["shared"],
                   "ok": False, "failovers": 0}

            def one_turn():
                if transport == "http":
                    toks, ttft, tpots = _stream_http(
                        base_url, deployment, s["sid"], payload, timeout)
                elif transport in ("handle", "resilient"):
                    toks, ttft, tpots, fo = _stream_handle(
                        handle, s["sid"], payload, timeout,
                        resilient=transport == "resilient")
                    rec["failovers"] = fo
                else:
                    raise ValueError(f"unknown transport {transport!r}")
                rec.update(ok=len(toks) > 0, tokens=toks, ttft_s=ttft,
                           tpots_s=tpots)
                return toks

            try:
                if tracing:
                    from ray_tpu.util import tracing as trc

                    # pre-activate a W3C-width trace id so the root
                    # span survives round-tripping through a conformant
                    # proxy byte-identical (trace() alone would mint a
                    # narrower internal id)
                    tok = trc.activate((trc.new_trace_id(), None))
                    try:
                        with trc.trace("traffic.turn", session=s["sid"],
                                       turn=turn) as span:
                            rec["trace_id"] = span.trace_id
                            toks = one_turn()
                    finally:
                        trc.deactivate(tok)
                else:
                    toks = one_turn()
                ctx = ctx + toks
            except Exception as e:  # noqa: BLE001 — a failed stream is DATA
                rec["error"] = f"{type(e).__name__}: {e}"
            with rec_lock:
                records.append(rec)
            if not rec["ok"]:
                return            # a dead turn ends the session

    threads = []
    for s in sorted(trace["sessions"], key=lambda x: x["arrival_s"]):
        delay = s["arrival_s"] * time_scale - (time.perf_counter() - t0)
        if delay > 0:
            time.sleep(delay)
        th = threading.Thread(target=run_session, args=(s,), daemon=True)
        th.start()
        threads.append(th)
    for th in threads:
        th.join(timeout=timeout)
    return {"records": records, "wall_s": time.perf_counter() - t0}


# ---------------------------------------------------------------------------
# reporting


def _pct(vals: List[float], p: float) -> Optional[float]:
    if not vals:
        return None
    vals = sorted(vals)
    i = min(len(vals) - 1, max(0, math.ceil(p / 100.0 * len(vals)) - 1))
    return vals[i]


def summarize(result: Dict[str, Any]) -> Dict[str, Any]:
    """Trace-replay report row (the bench/CI surface): goodput +
    latency tails + failure/failover counts."""
    recs = result["records"]
    ok = [r for r in recs if r.get("ok")]
    ttfts = [r["ttft_s"] for r in ok]
    tpots = [t for r in ok for t in r.get("tpots_s", ())]

    def ms(v):
        return round(v * 1e3, 1) if v is not None else None

    return {
        "traffic_requests": len(recs),
        "traffic_completed": len(ok),
        "traffic_failed": len(recs) - len(ok),
        "traffic_goodput_rps": round(len(ok) / max(result["wall_s"], 1e-6),
                                     2),
        "traffic_wall_s": round(result["wall_s"], 2),
        "traffic_ttft_p50_ms": ms(_pct(ttfts, 50)),
        "traffic_ttft_p99_ms": ms(_pct(ttfts, 99)),
        "traffic_tpot_p50_ms": ms(_pct(tpots, 50)),
        "traffic_tpot_p99_ms": ms(_pct(tpots, 99)),
        "traffic_failovers": sum(r.get("failovers", 0) for r in recs),
        "traffic_tokens": sum(len(r.get("tokens", ())) for r in ok),
    }


def scrape_counter(scrape: str, name: str) -> float:
    """Sum a counter/gauge family across its tag series on a raw
    /metrics scrape body."""
    total = 0.0
    for line in scrape.splitlines():
        if line.startswith(name) and not line.startswith("#"):
            head = line.split(" ")[0]
            if head == name or head.startswith(name + "{"):
                try:
                    total += float(line.rsplit(" ", 1)[1])
                except ValueError:
                    pass
    return total


def scrape_hit_rate(scrape: str) -> float:
    hit = scrape_counter(scrape, "ray_tpu_llm_prefix_hit_tokens")
    miss = scrape_counter(scrape, "ray_tpu_llm_prefix_miss_tokens")
    return hit / (hit + miss) if hit + miss else 0.0


# ---------------------------------------------------------------------------
# live-cluster plumbing shared with scripts/traffic_smoke.py — ONE deploy
# shape and ONE scrape-wait, so the CI gate and the bench row can't drift


def deploy_llm_app(replicas: int, engine_cfg: dict, **deploy_overrides):
    """Deploy the LLMServer app the harness/smoke drive and warm one
    replica's compile caches. Returns the routing handle."""
    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.serve.llm import LLMServer

    opts = dict(num_replicas=replicas, max_concurrent_queries=16,
                health_check_timeout_s=120)
    opts.update(deploy_overrides)
    app = serve.deployment(**opts)(LLMServer).bind(
        model="gpt-tiny", engine_config=engine_cfg)
    handle = serve.run(app, timeout=300)
    ray_tpu.get(handle.remote({"tokens": [1, 2, 3], "max_tokens": 2}),
                timeout=300)
    return handle


def wait_for_scrape(needle: str, timeout: float = 30.0) -> str:
    """Start/reuse the head metrics server and poll /metrics until
    ``needle`` appears (the worker->head delta ship is periodic) or the
    timeout lapses. Returns the last scrape body either way."""
    from ray_tpu.util import metrics as metrics_mod

    mhost, mport = metrics_mod.start_metrics_server()
    deadline = time.time() + timeout
    scrape = ""
    while True:
        with urllib.request.urlopen(
                f"http://{mhost}:{mport}/metrics", timeout=10) as r:
            scrape = r.read().decode()
        if needle in scrape or time.time() > deadline:
            return scrape
        time.sleep(0.5)


# ---------------------------------------------------------------------------
# standalone run


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--sessions", type=int, default=40)
    ap.add_argument("--replicas", type=int, default=2)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--shared-frac", type=float, default=0.6)
    ap.add_argument("--prefix-len", type=int, default=24)
    ap.add_argument("--max-turns", type=int, default=3)
    ap.add_argument("--max-tokens", type=int, default=6)
    ap.add_argument("--transport", choices=("http", "handle", "resilient"),
                    default="http")
    ap.add_argument("--no-prefix-cache", action="store_true",
                    help="A/B: deploy with the radix cache disabled")
    ap.add_argument("--chaos", default="",
                    help="RAY_TPU_CHAOS spec (wire-level faults; pair "
                         "with --transport resilient)")
    ap.add_argument("--trace", action="store_true",
                    help="open a driver-rooted distributed-trace span "
                         "around every turn (propagated as traceparent "
                         "over http, via the handle context otherwise); "
                         "inspect with `ray_tpu trace --slowest 5`")
    ap.add_argument("--kill-replica-at", type=float, default=0.0,
                    help="kill a live replica N seconds into the replay "
                         "(seeded pick; use --transport resilient so "
                         "streams fail over instead of failing)")
    ap.add_argument("--json", default="", help="write the report row here")
    args = ap.parse_args()

    if args.chaos:
        os.environ["RAY_TPU_CHAOS"] = args.chaos

    import ray_tpu
    from ray_tpu import serve

    cfg = dict(ENGINE_CFG)
    if args.no_prefix_cache:
        cfg["prefix_cache"] = False
    trace = make_trace(args.sessions, args.seed,
                       shared_frac=args.shared_frac,
                       prefix_len=args.prefix_len,
                       max_turns=args.max_turns,
                       max_tokens=args.max_tokens)
    n_reqs = sum(len(s["chunks"]) for s in trace["sessions"])
    print(f"traffic_harness: {args.sessions} sessions / {n_reqs} requests "
          f"({args.shared_frac:.0%} shared-prefix), transport="
          f"{args.transport}, prefix_cache={cfg['prefix_cache']}")

    ray_tpu.init(num_cpus=max(4, args.replicas + 2))
    try:
        handle = deploy_llm_app(args.replicas, cfg)
        kwargs: Dict[str, Any] = dict(transport=args.transport,
                                      handle=handle, tracing=args.trace)
        if args.transport == "http":
            host, port = serve.start_http_proxy(port=0)
            kwargs["base_url"] = f"http://{host}:{port}"
        if args.kill_replica_at > 0:
            def killer():
                import random as _random

                time.sleep(args.kill_replica_at)
                try:
                    controller = ray_tpu.get_actor("SERVE_CONTROLLER")
                    _v, _q, reps = ray_tpu.get(
                        controller.get_replicas.remote("LLMServer"),
                        timeout=10)
                    if reps:
                        victim = _random.Random(args.seed).choice(reps)
                        print(f"traffic_harness: killing replica "
                              f"{victim._actor_id.hex()[:8]} mid-replay")
                        ray_tpu.kill(victim)
                except Exception as e:  # noqa: BLE001
                    print(f"traffic_harness: kill failed: {e}",
                          file=sys.stderr)
            threading.Thread(target=killer, daemon=True).start()
        result = replay(trace, **kwargs)
        row = summarize(result)

        scrape = wait_for_scrape(
            "" if args.no_prefix_cache else "ray_tpu_llm_prefix",
            timeout=20)
        row["prefix_hit_rate"] = round(scrape_hit_rate(scrape), 4)
        row["llm_preemptions"] = int(scrape_counter(
            scrape, "ray_tpu_llm_preemptions_total"))
        row["session_reroutes"] = int(scrape_counter(
            scrape, "ray_tpu_serve_session_reroutes_total"))

        print(json.dumps(row, indent=2))
        if args.json:
            with open(args.json, "w") as f:
                json.dump(row, f)
        if row["traffic_failed"]:
            failed = [r for r in result["records"] if not r.get("ok")]
            print(f"FAILED streams: {failed[:5]}", file=sys.stderr)
            return 1
        return 0
    finally:
        serve.shutdown()
        ray_tpu.shutdown()


if __name__ == "__main__":
    sys.exit(main())
