#!/usr/bin/env python
"""CI smoke for request-scoped distributed tracing (ISSUE 18 /
docs/OBSERVABILITY.md "Distributed tracing").

Live 2-node gate, run after perf_smoke: an in-process head plus one
REAL remote node agent, two prefix-cached LLMServer replicas, the real
HTTP proxy in front. Then:

- replays a bursty session trace with ``--trace`` semantics (a
  driver-rooted span per turn, forwarded as a W3C ``traceparent``
  header) through the REAL HTTP proxy, and asserts the head TraceStore
  holds >=1 tail-kept SLOW trace whose spans come from >=3 distinct
  processes (client driver, proxy actor, replica worker)
- kills one replica mid-replay while resilient streams are in flight
  and asserts >=1 trace was tail-kept for ``failover`` with BOTH hops
  stitched into one span tree: 2+ serve.route hops, a serve.failover
  span, engine spans from two distinct replica processes
- scrapes the REAL /metrics exposition, pulls a ``trace_id`` exemplar
  off a latency-histogram bucket, and resolves it over the head RPC
  the ``ray_tpu trace`` CLI uses (``trace_get``) back to the stored
  span tree — the p99-to-trace workflow end to end

Exit 0 = healthy; any assertion prints the evidence and exits 1.
Run: python scripts/trace_smoke.py   (CI invokes it after perf_smoke)
"""
import os
import re
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

# a 50ms slow bar makes real streamed turns "slower than SLO" so the
# tail sampler's always-keep path (not the probabilistic one) is what
# this gate exercises; must be set before ray_tpu.core.config imports
os.environ.setdefault("RTPU_TRACE_SLOW_THRESHOLD_S", "0.05")

from traffic_harness import (ENGINE_CFG, deploy_llm_app,  # noqa: E402
                             make_trace, replay, summarize,
                             wait_for_scrape)

N_SESSIONS = 10
KILL_AT_S = 1.0


def _kill_one_replica_after(delay_s: float, seed: int = 0):
    """Kill a seeded-random live replica ``delay_s`` into the replay —
    the traffic_harness --kill-replica-at move, as a thread."""
    import random

    import ray_tpu

    def killer():
        time.sleep(delay_s)
        try:
            controller = ray_tpu.get_actor("SERVE_CONTROLLER")
            _v, _q, reps = ray_tpu.get(
                controller.get_replicas.remote("LLMServer"), timeout=10)
            if reps:
                victim = random.Random(seed).choice(reps)
                print(f"trace_smoke: killing replica "
                      f"{victim._actor_id.hex()[:8]} mid-replay")
                ray_tpu.kill(victim)
        except Exception as e:  # noqa: BLE001
            print(f"trace_smoke: kill failed: {e}", file=sys.stderr)

    th = threading.Thread(target=killer, daemon=True)
    th.start()
    return th


def _span_names(detail):
    return [s.get("name", "") for s in detail.get("spans_detail", ())]


def main() -> int:
    import ray_tpu  # noqa: F401 — Cluster below owns init
    from ray_tpu import cli, serve
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.core.rpc import connect

    c = Cluster(head_resources={"CPU": 4.0})
    try:
        c.add_remote_node(num_cpus=4.0)
        handle = deploy_llm_app(2, ENGINE_CFG)
        host, port = serve.start_http_proxy(port=0)
        store = c.runtime.gcs.traces
        print(f"trace_smoke: 2 nodes up, proxy at {host}:{port}")

        # -- 1) traced replay through the real HTTP proxy ---------------
        trace = make_trace(N_SESSIONS, seed=5, max_turns=2, max_tokens=8)
        result = replay(trace, base_url=f"http://{host}:{port}",
                        transport="http", tracing=True)
        row = summarize(result)
        assert row["traffic_failed"] == 0, \
            [r for r in result["records"] if not r.get("ok")][:5]
        want_tids = {r["trace_id"] for r in result["records"]
                     if r.get("trace_id")}
        print(f"trace_smoke: http replay done — {row['traffic_completed']} "
              f"turns, {len(want_tids)} driver-rooted traces")

        # worker spans ride channel notifies; let stragglers land
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            kept = store.query(limit=500)["traces"]
            slow3 = [t for t in kept if t["keep_reason"] == "slow"
                     and t["procs"] >= 3]
            if slow3:
                break
            time.sleep(0.5)
        assert slow3, (
            f"no tail-kept slow trace with spans from >=3 processes; "
            f"kept={[(t['trace_id'][:8], t['keep_reason'], t['procs']) for t in kept]}")
        assert any(t["trace_id"] in want_tids for t in slow3), \
            "slow traces stored, but none match a replayed turn's trace id"
        pick = next(t for t in slow3 if t["trace_id"] in want_tids)
        detail = store.get(pick["trace_id"])
        names = _span_names(detail)
        for need in ("traffic.turn", "http.request", "serve.route",
                     "replica.exec", "llm.admit", "llm.retire"):
            assert need in names, f"span {need!r} missing: {names}"
        rendered = cli._render_trace_tree(detail, verbose=True)
        assert "http.request" in rendered and "llm.retire" in rendered, \
            rendered[:400]
        print(f"trace_smoke: slow trace {pick['trace_id'][:12]} OK — "
              f"{pick['spans']} spans / {pick['procs']} processes, "
              f"full proxy->router->replica->engine lifecycle")

        # -- 2) mid-stream replica kill => one trace, both hops ---------
        trace2 = make_trace(8, seed=11, max_turns=2, max_tokens=24)
        _kill_one_replica_after(KILL_AT_S)
        result2 = replay(trace2, handle=handle, transport="resilient",
                         tracing=True)
        row2 = summarize(result2)
        assert row2["traffic_failed"] == 0, \
            [r for r in result2["records"] if not r.get("ok")][:5]
        assert row2["traffic_failovers"] >= 1, row2
        deadline = time.monotonic() + 20
        fo_detail = None
        while time.monotonic() < deadline:
            fo = [t for t in store.query(limit=500)["traces"]
                  if t["keep_reason"] == "failover"]
            for t in fo:
                d = store.get(t["trace_id"])
                ns = _span_names(d)
                routes = ns.count("serve.route")
                # both hops' route spans + the failover marker record
                # DRIVER-side, so they are deterministic evidence; the
                # dead hop's replica/engine spans only arrive if the
                # kill landed after they shipped, so a second replica
                # pid is preferred, not required
                hop_pids = {s.get("pid") for s in d["spans_detail"]
                            if str(s.get("name", "")).startswith(
                                ("replica.", "llm."))}
                if routes >= 2 and "serve.failover" in ns:
                    if fo_detail is None or len(hop_pids) >= 2:
                        fo_detail = (t, routes, hop_pids)
                    if len(hop_pids) >= 2:
                        break
            if fo_detail and (len(fo_detail[2]) >= 2
                              or time.monotonic() > deadline - 10):
                break
            time.sleep(0.5)
        assert fo_detail, \
            ("no failover-kept trace stitching both hops; failover "
             f"traces: {[t['trace_id'][:8] for t in fo]}")
        t, routes, hop_pids = fo_detail
        print(f"trace_smoke: failover trace {t['trace_id'][:12]} OK — "
              f"{routes} route hops, serve.failover span present, "
              f"replica pids {sorted(p for p in hop_pids if p)}")

        # -- 3) /metrics exemplar resolves to a stored trace ------------
        scrape = wait_for_scrape('# {trace_id="')
        pat = (r'(ray_tpu_[a-z0-9_]+)_bucket\{[^}]*\}\s+\S+'
               r'\s+#\s+\{trace_id="([0-9a-f]+)"\}')
        hits = re.findall(pat, scrape)
        assert hits, "no trace_id exemplar on any histogram bucket"
        fams = {f for f, _ in hits}
        assert "ray_tpu_llm_ttft_seconds" in fams, \
            f"no TTFT exemplar crossed the worker->head delta path: {fams}"
        resolved = 0
        for fam, tid in hits:
            det = store.get(tid)
            if det and det.get("spans_detail"):
                resolved += 1
        assert resolved, f"no exemplar trace id resolves: {hits[:5]}"
        print(f"trace_smoke: {len(hits)} bucket exemplars on "
              f"{len(fams)} families, {resolved} resolve to stored traces")

        # -- 4) the CLI's own head RPCs, over the wire ------------------
        addr = c.runtime.enable_remote_nodes()
        ch = connect(addr, name="trace-smoke")
        q = ch.call("traces_query", {"slowest": 3}, timeout=30)
        assert q["traces"], q
        det = ch.call("trace_get", q["traces"][0]["trace_id"], timeout=30)
        assert det and det.get("spans_detail"), det
        snap = ch.call("perf_snapshot", {}, timeout=30)
        assert snap.get("traces", {}).get("kept_traces", 0) >= 1, \
            snap.get("traces")
        top = cli._render_top(snap, None, 2.0)
        assert "tracing:" in top, top[:400]
        st = store.stats()
        print(f"trace_smoke: head RPCs OK — store kept="
              f"{st['kept_traces']}/{st['total_traces']} "
              f"bytes={st['bytes']}")
        serve.shutdown()
    finally:
        c.shutdown()
    print("trace_smoke: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
