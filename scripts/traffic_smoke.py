#!/usr/bin/env python
"""CI smoke for traffic-shaped serving (ISSUE 14 / docs/LLM_SERVE.md
"Prefix caching & sessions").

Live 2-replica gate: a 40-session bursty trace (Poisson-burst arrivals,
Zipf session lengths, 60% shared-prefix mix, multi-turn contexts)
replays through the REAL HTTP proxy against prefix-cached LLMServer
replicas with session-aware routing, asserting:

- every streamed response is TOKEN-IDENTICAL to a cache-off
  ground-truth engine replaying the same trace driver-locally (the
  radix cache + session affinity change COST, never tokens)
- the scrape-level prefix-cache hit rate clears 0.4 — shared prefixes
  and re-sent multi-turn contexts really do land on cached KV
- zero leaked or overcounted blocks: on every replica, post-replay
  occupancy equals exactly the cache-resident block count (refcounted
  sharing counts each block once, never above pool capacity)
- the session-affinity table pinned sessions to replicas, and the new
  ray_tpu_llm_prefix_* / cache_hit_rate / session_reroutes series
  crossed the worker -> head delta path onto a real /metrics scrape

Exit 0 = healthy; any assertion prints the evidence and exits 1.
Run: python scripts/traffic_smoke.py  (CI invokes it after
sharding_smoke)
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from traffic_harness import (ENGINE_CFG, deploy_llm_app,  # noqa: E402
                             make_trace, reference_completions, replay,
                             scrape_counter, scrape_hit_rate, summarize,
                             wait_for_scrape)

N_SESSIONS = 40
SHARED_FRAC = 0.6
HIT_RATE_FLOOR = 0.4


def main() -> int:
    trace = make_trace(N_SESSIONS, seed=3, shared_frac=SHARED_FRAC,
                       max_turns=3, max_tokens=6)
    n_reqs = sum(len(s["chunks"]) for s in trace["sessions"])
    shared = sum(1 for s in trace["sessions"] if s["shared"])
    print(f"traffic_smoke: {N_SESSIONS} sessions / {n_reqs} requests, "
          f"{shared} shared-prefix")

    # cache-OFF ground truth, computed before the cluster exists: greedy
    # decode on the same seed-0 weights defines THE correct stream for
    # every (session, turn)
    want = reference_completions(trace)

    import ray_tpu
    from ray_tpu import serve

    ray_tpu.init(num_cpus=4)
    try:
        handle = deploy_llm_app(2, ENGINE_CFG)
        host, port = serve.start_http_proxy(port=0)
        print(f"traffic_smoke: proxy at {host}:{port}, replaying...")

        t0 = time.perf_counter()
        result = replay(trace, base_url=f"http://{host}:{port}",
                        transport="http")
        row = summarize(result)
        print(f"traffic_smoke: replay done in {time.perf_counter()-t0:.1f}s "
              f"goodput={row['traffic_goodput_rps']}rps "
              f"p99_ttft={row['traffic_ttft_p99_ms']}ms "
              f"p99_tpot={row['traffic_tpot_p99_ms']}ms")
        assert row["traffic_failed"] == 0, \
            [r for r in result["records"] if not r.get("ok")][:5]
        assert row["traffic_completed"] == n_reqs, row

        # -- token identity vs the cache-off ground truth -----------------
        for rec in result["records"]:
            w = want[rec["sid"]][rec["turn"]]
            assert rec["tokens"] == w, (
                f"{rec['sid']} turn {rec['turn']}: cached serving DIVERGED"
                f"\n  got  {rec['tokens']}\n  want {w}")
        print(f"traffic_smoke: all {n_reqs} responses token-identical "
              f"to cache-off ground truth")

        # -- zero leaked / overcounted blocks on EVERY replica ------------
        controller = ray_tpu.get_actor("SERVE_CONTROLLER")
        _v, _q, reps = ray_tpu.get(
            controller.get_replicas.remote("LLMServer"), timeout=30)
        assert len(reps) == 2, f"expected 2 routable replicas: {reps}"
        deadline = time.monotonic() + 30
        for r in reps:
            while True:     # engines drain their last decode steps
                st = ray_tpu.get(r.handle_request.remote("stats", (), {}),
                                 timeout=60)
                if st["queue_depth"] == 0 or time.monotonic() > deadline:
                    break
                time.sleep(0.2)
            assert st["kv_blocks_used"] == st["prefix_blocks_resident"], \
                (f"leak: {st['kv_blocks_used']} blocks used vs "
                 f"{st['prefix_blocks_resident']} cache-resident — a "
                 f"retired sequence kept references: {st}")
            assert st["kv_blocks_used"] <= st["kv_blocks_total"], \
                f"overcount above pool capacity: {st}"
            print(f"traffic_smoke: replica {st['engine']}: "
                  f"{st['kv_blocks_used']} blocks used == cache-resident, "
                  f"hit_rate={st['cache_hit_rate']}")

        # -- scrape: hit rate + new metric families -----------------------
        scrape = wait_for_scrape("ray_tpu_llm_prefix_hit_tokens")
        for name in ("ray_tpu_llm_prefix_hit_tokens",
                     "ray_tpu_llm_prefix_miss_tokens",
                     "ray_tpu_llm_cache_hit_rate"):
            assert name in scrape, f"{name} missing from the head scrape"
        hit_rate = scrape_hit_rate(scrape)
        reroutes = scrape_counter(scrape,
                                  "ray_tpu_serve_session_reroutes_total")
        print(f"traffic_smoke: scrape hit_rate={hit_rate:.3f} "
              f"(floor {HIT_RATE_FLOOR}), session_reroutes={int(reroutes)}")
        assert hit_rate > HIT_RATE_FLOOR, \
            (f"hit rate {hit_rate:.3f} <= {HIT_RATE_FLOOR}: the shared-"
             f"prefix mix is not landing on cached KV")
        serve.shutdown()
    finally:
        ray_tpu.shutdown()
    print("traffic_smoke: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
