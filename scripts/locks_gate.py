#!/usr/bin/env python
"""Dynamic companion to the static lock rules (GC030-033, GC050-054):
run the direct-dispatch suite under ``RAY_TPU_DEBUG_LOCKS=1`` (the
instrumented-lock factory: per-thread acquisition stacks + role-level
lock-order graph, docs/GRAFTCHECK.md) and assert

  1. ZERO lock-order inversions were reported anywhere in the run —
     driver and worker processes alike (their warnings reach the
     captured output through the driver log mirror), and
  2. every DYNAMICALLY OBSERVED held->acquired role edge is a subset of
     the STATIC lock-order graph (``graftcheck locks --json``): the
     graph GC052 proves acyclic must describe every ordering the
     running system actually exercises, or the proof is about the
     wrong graph.

For (2) each process appends its observed ``held -> acq`` role pairs to
``RAY_TPU_LOCK_ORDER_DUMP`` at exit (O_APPEND — workers and the driver
share one file). A dynamic edge (h, a) is covered when a static edge
(H, A) matches it role-pattern-wise (shard families carry fnmatch
wildcards, e.g. ``gcs.events.s*``), or when h and a are two shards of
ONE wildcard family — same-role edges are deliberately collapsed out of
the static graph (a family's shards are ordered by index, not by the
pairwise graph).

The static pass proves release-on-every-path per function; this gate
proves the cross-thread ORDER discipline the CFG cannot see, on the
suite with the densest lock interleaving (per-caller lanes, peer
caches, sharded head loops).

Exit status: 0 = suite green, zero inversions, dynamic graph covered;
1 otherwise (uncovered edges are listed with the static hops closest
to them).
"""
import fnmatch
import json
import os
import subprocess
import sys
import tempfile

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
MARKER = "lock-order inversion"


def _read_dynamic_edges(path: str):
    """Parse the dump file: one 'held -> acq' role pair per line."""
    edges = set()
    if not os.path.exists(path):
        return edges
    with open(path) as f:
        for ln in f:
            if " -> " not in ln:
                continue
            held, acq = ln.strip().split(" -> ", 1)
            if held and acq:
                edges.add((held, acq))
    return edges


def _static_graph():
    """(edges, roles) from ``graftcheck locks --json`` over ray_tpu/."""
    out = os.path.join(tempfile.gettempdir(), "locks_gate_static.json")
    proc = subprocess.run(
        [sys.executable, "-m", "ray_tpu.devtools.graftcheck", "locks",
         "--json", "--out", out, "ray_tpu/"],
        cwd=ROOT, capture_output=True, text=True)
    if proc.returncode not in (0, 1):  # 1 = findings elsewhere; graph still valid
        raise RuntimeError(f"graftcheck locks failed:\n{proc.stderr[-2000:]}")
    with open(out) as f:
        data = json.load(f)
    return data["edges"], data.get("roles", [])


def _covered(dyn, static_edges, roles) -> bool:
    held, acq = dyn
    for e in static_edges:
        if fnmatch.fnmatch(held, e["src"]) and fnmatch.fnmatch(acq, e["dst"]):
            return True
    # two shards of one wildcard family: the static graph collapses
    # same-role edges (intra-family order is by shard index)
    for r in roles:
        if "*" in r and fnmatch.fnmatch(held, r) and fnmatch.fnmatch(acq, r):
            return True
    return False


def main() -> int:
    dump = os.path.join(tempfile.gettempdir(),
                        f"locks_gate_order_{os.getpid()}.txt")
    if os.path.exists(dump):
        os.unlink(dump)
    env = dict(os.environ)
    env["RAY_TPU_DEBUG_LOCKS"] = "1"
    env["RAY_TPU_LOCK_ORDER_DUMP"] = dump
    env.setdefault("JAX_PLATFORMS", "cpu")
    proc = subprocess.run(
        [sys.executable, "-m", "pytest", "tests/test_dispatch_direct.py",
         "-q", "-p", "no:cacheprovider", "-p", "no:randomly"],
        cwd=ROOT, env=env, capture_output=True, text=True)
    out = proc.stdout + proc.stderr
    sys.stdout.write(out[-4000:])
    inversions = [ln for ln in out.splitlines() if MARKER in ln]
    if proc.returncode != 0:
        print(f"locks_gate: FAIL — pytest exited {proc.returncode}")
        return 1
    if inversions:
        print(f"locks_gate: FAIL — {len(inversions)} lock-order "
              f"inversion(s) reported under RAY_TPU_DEBUG_LOCKS=1:")
        for ln in inversions[:10]:
            print("  " + ln.strip())
        return 1

    dyn_edges = _read_dynamic_edges(dump)
    try:
        static_edges, roles = _static_graph()
    except RuntimeError as e:
        print(f"locks_gate: FAIL — {e}")
        return 1
    uncovered = sorted(d for d in dyn_edges
                       if not _covered(d, static_edges, roles))
    if uncovered:
        print(f"locks_gate: FAIL — {len(uncovered)} dynamically observed "
              f"lock-order edge(s) missing from the static graph "
              f"(GC052 proved the WRONG graph acyclic):")
        for held, acq in uncovered:
            print(f"  observed: {held} -> {acq}")
            near = [e for e in static_edges
                    if fnmatch.fnmatch(held, e["src"])
                    or fnmatch.fnmatch(acq, e["dst"])]
            for e in near[:4]:
                print(f"    static hop: {e['src']} -> {e['dst']} "
                      f"({e['path']}:{e['line']})")
        print("  -> teach rules_concurrency.py the acquisition pattern "
              "(receiver typing / container value types), or the order "
              "proof does not bind the running system")
        return 1
    print(f"locks_gate: OK — suite green, zero lock-order inversions, "
          f"{len(dyn_edges)} observed order edge(s) all inside the "
          f"{len(static_edges)}-edge static graph")
    try:
        os.unlink(dump)
    except OSError:
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
