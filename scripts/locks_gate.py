#!/usr/bin/env python
"""Dynamic companion to the GC030-033 static lock-discipline rules: run
the direct-dispatch suite under ``RAY_TPU_DEBUG_LOCKS=1`` (the
instrumented-lock factory: per-thread acquisition stacks + role-level
lock-order graph, docs/GRAFTCHECK.md) and assert ZERO lock-order
inversions were reported anywhere in the run — driver and worker
processes alike (their warnings reach the captured output through the
driver log mirror).

The static pass proves release-on-every-path per function; this gate
proves the cross-thread ORDER discipline the CFG cannot see, on the
suite with the densest lock interleaving (per-caller lanes, peer
caches, sharded head loops).

Exit status: 0 = suite green and zero inversions; 1 otherwise.
"""
import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
MARKER = "lock-order inversion"


def main() -> int:
    env = dict(os.environ)
    env["RAY_TPU_DEBUG_LOCKS"] = "1"
    env.setdefault("JAX_PLATFORMS", "cpu")
    proc = subprocess.run(
        [sys.executable, "-m", "pytest", "tests/test_dispatch_direct.py",
         "-q", "-p", "no:cacheprovider", "-p", "no:randomly"],
        cwd=ROOT, env=env, capture_output=True, text=True)
    out = proc.stdout + proc.stderr
    sys.stdout.write(out[-4000:])
    inversions = [ln for ln in out.splitlines() if MARKER in ln]
    if proc.returncode != 0:
        print(f"locks_gate: FAIL — pytest exited {proc.returncode}")
        return 1
    if inversions:
        print(f"locks_gate: FAIL — {len(inversions)} lock-order "
              f"inversion(s) reported under RAY_TPU_DEBUG_LOCKS=1:")
        for ln in inversions[:10]:
            print("  " + ln.strip())
        return 1
    print("locks_gate: OK — suite green, zero lock-order inversions "
          "under instrumented locks")
    return 0


if __name__ == "__main__":
    sys.exit(main())
