#!/usr/bin/env python
"""CI smoke for elastic capacity on preemptible pods (ISSUE 12).

A live cluster where the AUTOSCALER — not the test — owns capacity:

1. **Demand-driven scale-up to real nodes**: an LLMServer deployment
   asks for 2 replicas sized so the second cannot fit on the head; the
   parked actor creation is the autoscaler's demand signal, a
   FakeSliceProvider node agent (separate OS process) is launched, and
   the replica lands on it.
2. **Scale-down through a scripted preemption**: the provider schedules
   a preemption (notice now, SIGKILL at +grace). The reconcile loop
   turns the notice into the NODE_PREEMPTING drain: the serve replica
   on the doomed node drains (router stops assigning it new streams;
   4 concurrent `resilient_stream` clients riding it finish with every
   token), the live pipeline-training engine shrinks dp=2 -> 1 at its
   next step boundary (hands-off, `enable_elastic`), and the node exits
   CLEANLY before the axe (`ray_tpu_node_preemptions_total`
   outcome=drained).
3. **Scale-up again**: the drained replica's replacement parks, a
   second node launches, and the engine grows back to dp=2 on the
   join event.
4. **Zero failed requests + fixed-size final-params check**: every
   stream is token-identical to a driver-local ground-truth engine, no
   step of the training loop failed, and the post-scale-up trajectory +
   final params are BIT-IDENTICAL to a fixed-size dp=2 engine restored
   from the same checkpoint.

Exit 0 = healthy; any assertion prints the evidence and exits 1.
Run: python scripts/elastic_smoke.py   (CI invokes it after chaos_smoke)
"""
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _mlp_pure_dp(width: int, M: int, mb_size: int):
    """Single-chunk (G=1) engine pieces: a pure data-parallel pipeline."""
    import jax
    import jax.numpy as jnp

    k = jax.random.PRNGKey(0)

    def fn(p, x, targets):
        return jnp.mean((jnp.tanh(x @ p["w"]) @ p["v"] - targets) ** 2)

    params = [{
        "w": jax.random.normal(jax.random.fold_in(k, 1),
                               (width, width)) * 0.3,
        "v": jax.random.normal(jax.random.fold_in(k, 2),
                               (width, width)) * 0.3,
    }]
    xs = jax.random.normal(jax.random.fold_in(k, 5), (M * mb_size, width))
    w_true = jax.random.normal(jax.random.fold_in(k, 6),
                               (width, width)) * 0.5
    ys = jnp.tanh(xs @ w_true)
    mbs = [xs[i * mb_size:(i + 1) * mb_size] for i in range(M)]
    tgts = [ys[i * mb_size:(i + 1) * mb_size] for i in range(M)]
    return [fn], params, mbs, tgts


def main() -> int:  # noqa: PLR0915 — one linear smoke story
    import tempfile

    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.autoscaler import (AutoscalerConfig, FakeSliceProvider,
                                    StandardAutoscaler)
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.serve.llm import (EngineConfig, LLMEngine, LLMServer,
                                   build_model, resilient_stream)
    from ray_tpu.train import CompiledPipelineEngine
    from ray_tpu.util import metrics

    c = Cluster(head_resources={"CPU": 3.5, "replica_slot": 1.0,
                                "stage_slot": 1.0},
                system_config={"health_check_period_s": 0.3})
    provider = None
    sc = None
    try:
        provider = FakeSliceProvider(
            c.runtime, resources_per_node={"CPU": 3.0, "replica_slot": 1.0,
                                "stage_slot": 1.0})
        sc = StandardAutoscaler(c.runtime, provider, AutoscalerConfig(
            min_workers=0, max_workers=2, idle_timeout_s=120.0,
            update_interval_s=0.4)).start()

        # -- phase 1: serve demand pulls a real node out of the provider
        app = serve.deployment(
            num_replicas=2, health_check_period_s=0.5,
            health_check_timeout_s=2.0,
            ray_actor_options={"num_cpus": 1.0,
                               "resources": {"replica_slot": 1.0}})(
            LLMServer).bind(
            model="gpt-tiny",
            engine_config={"max_batch": 4, "num_blocks": 64})
        h = serve.run(app, timeout=300)
        deadline = time.monotonic() + 240
        while serve.status()["LLMServer"]["running"] != 2:
            assert time.monotonic() < deadline, "replicas never came up"
            time.sleep(0.5)
        nodes1 = provider.non_terminated_nodes()
        assert len(nodes1) == 1, (
            f"serve demand should have launched exactly 1 provider node, "
            f"got {len(nodes1)}")
        doomed = nodes1[0]
        on_doomed = [a.actor_id.hex() for a in
                     c.runtime.gcs.actors_on_node(doomed)]
        assert on_doomed, "no replica landed on the autoscaled node"
        print(f"scale-up OK: node {doomed.hex()[:8]} launched by serve "
              f"demand, hosts {len(on_doomed)} actor(s)")

        # -- ground truth for the streams (chaos_smoke pattern)
        n_clients, max_tokens = 4, 48
        prompts = [[2, 5, 9], [1, 1, 4], [7, 3], [4, 8, 6, 2]]
        model, params = build_model("gpt-tiny", seed=0)
        ref = LLMEngine(model, params,
                        EngineConfig(max_batch=4, num_blocks=64),
                        name="truth")
        streams = [ref.add_request(p, max_tokens=max_tokens, eos_id=None)
                   for p in prompts]
        ref.run_until_idle(timeout=300)
        truth = [s.tokens(timeout=60) for s in streams]
        print("ground truth computed")

        # -- phase 2: live training engine, elastic, spread across nodes
        # M here is the GLOBAL microbatch count (dp * num_microbatches):
        # invariant across every resize the run rides through
        fns, sp, mbs, tgts = _mlp_pure_dp(16, M=8, mb_size=4)
        import optax

        ckpt_dir = tempfile.mkdtemp(prefix="elastic_smoke_ck_")
        eng = CompiledPipelineEngine(
            fns, sp, optax.adam(1e-2), num_microbatches=4, dp=2,
            channel_bytes=1 << 18, resources_per_stage={"CPU": 0.5, "stage_slot": 1.0},
            checkpoint_dir=ckpt_dir, checkpoint_every=0)
        eng.enable_elastic(min_dp=1, max_dp=2, grow_on_join=True)
        n_on_doomed = sum(1 for row in eng._plans for p in row
                          if p.node.node_id == doomed)
        assert n_on_doomed >= 1, \
            "no stage actor landed on the provider node"
        dp_seen = []
        losses = []
        train_err = []
        stop = threading.Event()
        boundary = threading.Event()

        def train_loop():
            try:
                while not stop.is_set():
                    losses.append(eng.step(mbs, tgts, timeout=120))
                    dp_seen.append(eng.dp)
                    boundary.set()
            except BaseException as e:  # noqa: BLE001 — asserted below
                train_err.append(e)

        trainer = threading.Thread(target=train_loop, name="train")
        trainer.start()
        boundary.wait(120)
        assert dp_seen and dp_seen[-1] == 2, (
            f"first step never landed: err={train_err!r} "
            f"losses={losses} dp={dp_seen}")

        # -- phase 3: clients stream while the scale-down is scripted
        gens = [resilient_stream(h, {"tokens": prompts[i],
                                     "max_tokens": max_tokens,
                                     "eos_id": None})
                for i in range(n_clients)]
        got = [[] for _ in range(n_clients)]
        cerrs = [None] * n_clients

        def client(i):
            try:
                for tok in gens[i]:
                    got[i].append(tok)
            except BaseException as e:  # noqa: BLE001 — asserted below
                cerrs[i] = e

        cthreads = [threading.Thread(target=client, args=(i,))
                    for i in range(n_clients)]
        for t in cthreads:
            t.start()
        deadline = time.monotonic() + 240
        while any(len(g) < 2 for g in got):  # prefills compiled, flowing
            assert time.monotonic() < deadline, "streams never started"
            time.sleep(0.2)

        # grace generous enough that the drain (streams finishing on the
        # marked replica) beats the axe on a slow CI box — the premature-
        # axe race is tests/test_elastic.py's job, not this gate's
        print(f"scripting preemption of {doomed.hex()[:8]} "
              f"(grace 90s) with 4 live streams + dp=2 training")
        provider.schedule_preemption(doomed, notice_in_s=0.0, grace_s=90.0)

        # the training loop must shrink hands-off at a step boundary
        deadline = time.monotonic() + 60
        while not (dp_seen and dp_seen[-1] == 1):
            assert not train_err, f"training failed: {train_err}"
            assert time.monotonic() < deadline, \
                f"engine never shrank; dp history tail {dp_seen[-5:]}"
            time.sleep(0.2)
        assert all(p.node.node_id != doomed
                   for row in eng._plans for p in row)
        print("training shrank to dp=1 off the doomed node")

        # streams complete with zero failures, token-identical
        for t in cthreads:
            t.join(timeout=420)
        assert not any(t.is_alive() for t in cthreads), "a client hung"
        assert not any(cerrs), f"client errors: {cerrs}"
        for i in range(n_clients):
            assert got[i] == truth[i], (
                f"stream {i} corrupted through the drain:\n"
                f"  got  {got[i]}\n  want {truth[i]}")
        print(f"4/4 streams complete + token-identical through the drain "
              f"({sum(g.failovers for g in gens)} failover(s))")

        # the doomed node leaves cleanly; a replacement node + replica
        # arrive; the engine grows back — all autoscaler-driven
        deadline = time.monotonic() + 180
        while True:
            live = provider.non_terminated_nodes()
            grown = dp_seen and dp_seen[-1] == 2
            serving = serve.status()["LLMServer"]["running"] == 2
            if doomed not in live and len(live) >= 1 and grown and serving:
                break
            assert not train_err, f"training failed: {train_err}"
            assert time.monotonic() < deadline, (
                f"scale-up incomplete: nodes={[n.hex()[:8] for n in live]} "
                f"dp={dp_seen[-1] if dp_seen else None} "
                f"serve={serve.status()['LLMServer']}")
            time.sleep(0.5)
        print("scale-down -> scale-up complete: node drained + replaced, "
              "dp back to 2, 2 replicas serving")

        # -- phase 4: final-params check vs the fixed-size run
        stop.set()
        trainer.join(timeout=120)
        assert not trainer.is_alive(), "training loop wedged"
        assert not train_err, f"training failed: {train_err}"
        ck = eng.save_checkpoint(blocking=True)
        tail = [eng.step(mbs, tgts, timeout=120) for _ in range(3)]
        import jax
        import numpy as np

        params_a = eng.get_params()
        step_at_ck = CompiledPipelineEngine.load_checkpoint(ck)["step"]
        eng.shutdown()
        fixed = CompiledPipelineEngine(
            fns, sp, optax.adam(1e-2), num_microbatches=4, dp=2,
            channel_bytes=1 << 18, resources_per_stage={"CPU": 0.5, "stage_slot": 1.0})
        try:
            assert fixed.restore(ck) == step_at_ck
            replay = [fixed.step(mbs, tgts, timeout=120) for _ in range(3)]
            params_b = fixed.get_params()
        finally:
            fixed.shutdown()
        assert tail == replay, (
            f"elastic tail diverged from the fixed-size run: "
            f"{tail} vs {replay}")
        for a, b in zip(jax.tree.leaves(params_a),
                        jax.tree.leaves(params_b)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        print(f"final-params check OK: elastic tail {tail} == fixed-size "
              f"replay (bitwise)")

        body = metrics._render()
        assert 'ray_tpu_node_preemptions_total{outcome="drained"}' in body, \
            "preemption not counted as drained"
        assert "ray_tpu_resize_seconds" in body, "resize metric missing"
        for direction in ("shrink", "grow"):
            assert f'direction="{direction}"' in body, \
                f"no {direction} resize recorded"
        serve.shutdown()
        print("elastic smoke OK")
        return 0
    finally:
        try:
            if sc is not None:
                sc.stop()
            if provider is not None:
                provider.shutdown()
        finally:
            c.shutdown()


if __name__ == "__main__":
    sys.exit(main())
