"""Achievable-peak calibration for the bench chip — the reproducible
artifact behind docs/PERF_NOTES.md's "nominal vs achievable" analysis.

Measures, on the attached device:
  1. sustained bf16 matmul throughput on clean large shapes (the
     best-case MXU number this chip will actually deliver): dependent
     N- and 2N-length matmul chains plus independent dispatches, with
     the 2N-minus-N delta (median of 3) as the headline — it cancels
     the tunnel's fixed per-dispatch overhead that skews raw probes
     3-4x low;
  2. the nominal peak used as the MFU denominator in bench.py;
  3. the GPT-2 bench step's implied sustained TF/s.

Prints ONE JSON line:
  {"nominal_tflops": .., "achievable_tflops": .., "achievable_frac": ..,
   "model_tflops": .., "mfu_nominal": .., "mfu_achievable": ..}

Measured this way the v5e behind the tunnel reaches 80-100% of its
197 TF/s nominal — so mfu_achievable tracks mfu_nominal and the
nominal denominator is honest (the round-4 "72-75 TF/s ceiling" was a
single-dispatch measurement artifact; docs/PERF_NOTES.md round 5).
Run this whenever the bench chip changes.

Usage: python scripts/mfu_calibrate.py  (30-60 s on the tunnel device)
"""
import functools
import json
import sys
import time

import jax
import jax.numpy as jnp

sys.path.insert(0, __file__.rsplit("/", 2)[0])


def _sync(x):
    # block_until_ready does not block on the tunnel backend; a small
    # device->host read does (docs/PERF_NOTES.md)
    return jax.device_get(jnp.sum(x[..., :1]))


def measure_matmul_peak(n: int = 8192, iters: int = 48) -> dict:
    """Sustained TF/s on a clean [n,n]x[n,n] bf16 matmul, three ways."""
    a = jnp.ones((n, n), jnp.bfloat16)
    b = jnp.ones((n, n), jnp.bfloat16)
    flops = 2 * n * n * n

    mm = jax.jit(lambda a, b: a @ b)
    _sync(mm(a, b))  # compile

    # method 1: dependent chain, one dispatch — each output FEEDS the
    # next (scaled so ones stay ones), so neither loop-invariant
    # hoisting nor DCE can elide any matmul. (An earlier version used
    # `* 0 + a` re-anchoring / an unused a@b per step — both of which
    # XLA may legally optimize away; numbers from those were unstable
    # in iteration count, the tell.)
    @jax.jit
    def chain(x, b):
        def body(x, _):
            return (x @ b) * jnp.bfloat16(1.0 / n), None

        x, _ = jax.lax.scan(body, x, None, length=iters)
        return x

    # method 2: independent back-to-back dispatches, wall-clocked
    # (upper-bounded by per-dispatch tunnel overhead)
    _sync(chain(a, b))
    t0 = time.perf_counter()
    outs = [mm(a, b) for _ in range(iters)]
    _sync(outs[-1])
    dt2 = (time.perf_counter() - t0) / iters

    # method 3: the dependent chain at 2x length — comparing its TF/s
    # with the N-chain's detects elision (they'd diverge wildly) and
    # feeds the delta below
    @jax.jit
    def chain2(x, b):
        def body(x, _):
            return (x @ b) * jnp.bfloat16(1.0 / n), None

        x, _ = jax.lax.scan(body, x, None, length=2 * iters)
        return x

    _sync(chain2(a, b))

    # headline: the 2N-minus-N delta cancels the fixed per-dispatch
    # overhead (tunnel RTT) that skews raw chains low. The overhead
    # noise (~0.1-0.3 s) rivals the signal, so sample 3x and take the
    # median; a swamped delta falls back to the raw 2N chain (a lower
    # bound, never absurd).
    deltas = []
    t1s, t3s = [], []
    for _ in range(3):
        t0 = time.perf_counter()
        _sync(chain(a, b))
        t1 = time.perf_counter() - t0
        t0 = time.perf_counter()
        _sync(chain2(a, b))
        t3 = time.perf_counter() - t0
        t1s.append(t1)
        t3s.append(t3)
        deltas.append(t3 - t1)
    deltas.sort()
    delta = deltas[1]
    if delta <= 0:
        delta = min(t3s) / 2
    dt1 = min(t1s) / iters
    dt3 = min(t3s) / (2 * iters)
    return {
        # labeled, unsorted: chain_N vs chain_2N must stay comparable
        # (divergence = elided work = invalid run)
        "methods_tflops": {
            "chain_N": round(flops / dt1 / 1e12, 1),
            "independent_dispatches": round(flops / dt2 / 1e12, 1),
            "chain_2N": round(flops / dt3 / 1e12, 1),
        },
        "achievable_tflops": round(flops / (delta / iters) / 1e12, 1),
    }


def nominal_peak(device) -> float:
    # same table as bench.py _peak_flops
    kind = getattr(device, "device_kind", "")
    table = {"TPU v5 lite": 197e12, "TPU v5e": 197e12, "TPU v4": 275e12,
             "TPU v5p": 459e12, "TPU v6e": 918e12}
    for k, v in table.items():
        if k in str(kind):
            return v
    return 197e12


def measure_model_step(batch: int = 40, steps: int = 10) -> dict:
    """The GPT-2 bench config's sustained TF/s (same path as bench.py)."""
    import optax

    from ray_tpu.models import GPT, GPTConfig

    cfg = GPTConfig.small(dtype=jnp.bfloat16, use_flash=True,
                          scan_layers=False, remat=False)
    model = GPT(cfg)
    tx = optax.adamw(3e-4, weight_decay=0.1)
    params = jax.jit(model.init)(jax.random.PRNGKey(0))
    opt_state = jax.jit(tx.init)(params)
    seq = 1024
    tokens = jax.random.randint(jax.random.PRNGKey(1), (batch, seq), 0,
                                cfg.vocab_size)
    targets = jnp.roll(tokens, -1, axis=1)
    num_chunks = max(1, (batch * seq) // 4096)
    while (batch * seq) % num_chunks:
        num_chunks -= 1

    def loss_fn(p, t, g):
        return model.loss_chunked(p, t, g, num_chunks=num_chunks)

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def step(params, opt_state, tokens, targets):
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens, targets)
        updates, opt_state = tx.update(grads, opt_state, params)
        import optax as _o

        return loss, _o.apply_updates(params, updates), opt_state

    loss, params, opt_state = step(params, opt_state, tokens, targets)
    float(loss)
    t0 = time.perf_counter()
    for _ in range(steps):
        loss, params, opt_state = step(params, opt_state, tokens, targets)
    float(loss)
    dt = (time.perf_counter() - t0) / steps
    tok_s = batch * seq / dt
    model_tflops = model.flops_per_token(seq) * tok_s / 1e12
    return {"sec_per_step": round(dt, 4), "model_tflops": round(model_tflops, 1)}


def main() -> None:
    dev = jax.devices()[0]
    peak = nominal_peak(dev)
    mat = measure_matmul_peak()
    mdl = measure_model_step()
    out = {
        "device": str(getattr(dev, "device_kind", dev)),
        "nominal_tflops": round(peak / 1e12, 1),
        **mat,
        **mdl,
        "achievable_frac": round(mat["achievable_tflops"] * 1e12 / peak, 4),
        "mfu_nominal": round(mdl["model_tflops"] * 1e12 / peak, 4),
        "mfu_achievable": round(
            mdl["model_tflops"] / mat["achievable_tflops"], 4),
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
