#!/usr/bin/env python
"""CI smoke for the log/introspection plane (graftcheck-style gate).

Spins up an in-process head plus one REAL remote node agent (a second
OS process over localhost TCP), runs chatty tasks on both nodes plus
one deliberately blocked in get(), then drives the actual CLI surfaces:

- `ray_tpu logs`            -> nonzero attributed lines from BOTH nodes
- `ray_tpu logs --task ID`  -> only that task's lines
- `ray_tpu stack`           -> every registered live worker present in
                               the merge, including the blocked one

Exit 0 = healthy; any assertion prints the evidence and exits 1.
Run: python scripts/logs_smoke.py   (CI invokes it after promlint)
"""
import contextlib
import io
import os
import re
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    import ray_tpu
    from ray_tpu.cli import main as cli_main
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.util import state
    from ray_tpu.util.scheduling_strategies import (
        NodeAffinitySchedulingStrategy)

    c = Cluster(head_resources={"CPU": 2.0})
    try:
        remote = c.add_remote_node(num_cpus=2.0)
        pin = NodeAffinitySchedulingStrategy(node_id=remote.node_id,
                                             soft=False)

        @ray_tpu.remote
        def chatty(tag):
            for i in range(5):
                print(f"smoke-{tag}-{i}")
            return ray_tpu.get_runtime_context().get_node_id()

        @ray_tpu.remote
        def slow_dep():
            time.sleep(6)
            return 1

        @ray_tpu.remote
        def blocked(x):
            return ray_tpu.get(x, timeout=120)  # graftcheck: disable=GC001

        dep = slow_dep.remote()
        blocked_ref = blocked.remote([dep])
        local_nid = ray_tpu.get(chatty.remote("local"), timeout=60)
        remote_nid = ray_tpu.get(
            chatty.options(scheduling_strategy=pin).remote("remote"),
            timeout=60)
        assert remote_nid == remote.node_id.hex()
        time.sleep(1.5)  # let batches land

        def cli(args):
            buf = io.StringIO()
            with contextlib.redirect_stdout(buf):
                rc = cli_main(args)
            return rc, buf.getvalue()

        # 1) nonzero lines, both nodes represented
        rc, out = cli(["logs", "--limit", "1000"])
        assert rc == 0, f"ray_tpu logs rc={rc}"
        lines = [ln for ln in out.splitlines() if "smoke-" in ln]
        assert len(lines) >= 10, f"expected >=10 smoke lines:\n{out}"
        assert any(local_nid[:8] in ln for ln in lines), out
        assert any(remote_nid[:8] in ln for ln in lines), out

        # 2) task filtering: only the remote chatty task's lines
        recs = state.logs(node_id=remote_nid, limit=1000)["records"]
        tids = {r["task_id"] for r in recs
                if r["line"].startswith("smoke-remote-")}
        assert len(tids) == 1 and "" not in tids, tids
        rc, out = cli(["logs", "--task", tids.pop(), "--limit", "1000"])
        assert rc == 0
        got = [ln for ln in out.splitlines() if "smoke-" in ln]
        assert got and all("smoke-remote-" in ln for ln in got), out

        # 3) stack merge covers every registered live worker
        live = set()
        for node in c.runtime.nodes.values():
            if not node.alive:
                continue
            for w in node.list_workers():
                if w.state not in ("starting", "dead"):
                    live.add(w.worker_id.hex()[:12])
        rc, out = cli(["stack"])
        assert rc == 0, f"ray_tpu stack rc={rc}"
        assert "=== driver pid=" in out
        reported = set(re.findall(r"=== worker ([0-9a-f]{12}) ", out))
        missing = live - reported
        assert not missing, (
            f"workers missing from stack merge: {missing}\n{out[-4000:]}")
        assert "get_many" in out or "fetch_one" in out, \
            "blocked-in-get worker's frames not visible"

        ray_tpu.get(blocked_ref, timeout=120)
        print(f"logs+stack smoke OK: {len(lines)} lines, "
              f"{len(reported)} workers in merge")
        return 0
    finally:
        c.shutdown()


if __name__ == "__main__":
    sys.exit(main())
