"""Core-runtime microbenchmarks (scheduler / object store / actor plane).

Mirrors the reference's microbenchmark harness (ref:
python/ray/_private/ray_perf.py:93-241 — tasks/s, actor calls/s, put
throughput, many-args/many-returns) so regressions in the task/actor/
object planes show up as numbers per round, tracked next to the model
bench in bench.py.

Run: python bench_core.py            (full)
     RTPU_BENCH_SMOKE=1 ...          (CI smoke: tiny counts)
Prints one JSON line per metric, then a summary JSON line.
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

SMOKE = os.environ.get("RTPU_BENCH_SMOKE", "") == "1"


def _rate(name: str, count: float, dt: float, unit: str) -> dict:
    rec = {"metric": name, "value": round(count / dt, 1), "unit": unit}
    print(json.dumps(rec), flush=True)
    return rec


def chain_roundtrip_us(n_iters: int = 200) -> dict:
    """3-actor chain round-trip: the dynamic `.remote()` path vs the same
    chain compiled into a cgraph pipeline (ISSUE 4 acceptance: compiled
    must be >= 5x faster). Assumes ray_tpu.init() already ran; returns
    {remote_chain_roundtrip_us, cgraph_chain_roundtrip_us, cgraph_speedup}
    for the bench JSON `detail`."""
    import ray_tpu
    from ray_tpu.cgraph import InputNode

    @ray_tpu.remote
    class Stage:
        def __init__(self, k):
            self.k = k

        def add(self, x):
            return x + self.k

    a, b, c = Stage.remote(1), Stage.remote(10), Stage.remote(100)

    # dynamic path: submit -> schedule -> lease -> RPC -> put -> get, x3
    ray_tpu.get(c.add.remote(b.add.remote(a.add.remote(0))), timeout=120)
    n_remote = max(10, n_iters // 4)
    t0 = time.perf_counter()
    for i in range(n_remote):
        out = ray_tpu.get(c.add.remote(b.add.remote(a.add.remote(i))),
                          timeout=120)
        assert out == i + 111
    remote_us = (time.perf_counter() - t0) / n_remote * 1e6

    # compiled path: pre-allocated channels + resident loops, zero
    # per-call scheduling
    with InputNode() as inp:
        dag = c.add.bind(b.add.bind(a.add.bind(inp)))
    compiled = dag.experimental_compile()
    try:
        for i in range(10):  # warm the loops + channel attachments
            compiled.execute(i).get(timeout=60)
        t0 = time.perf_counter()
        for i in range(n_iters):
            assert compiled.execute(i).get(timeout=60) == i + 111
        cgraph_us = (time.perf_counter() - t0) / n_iters * 1e6
    finally:
        compiled.teardown()
        for s in (a, b, c):
            ray_tpu.kill(s)  # release the leases for later bench phases
    return {
        "remote_chain_roundtrip_us": round(remote_us, 1),
        "cgraph_chain_roundtrip_us": round(cgraph_us, 1),
        "cgraph_speedup": round(remote_us / cgraph_us, 2),
    }


def multi_driver_tasks_per_s(n_drivers: int = 0,
                             calls_per_driver: int = 0) -> dict:
    """M DRIVER PROCESSES x pipelined actor calls (ISSUE 6): each driver
    is a worker-process task pipelining direct worker-to-worker calls to
    its own nop actor, so the measured bottleneck is the framework (and
    the box), not one submitting process. Returns the aggregate rate plus
    the direct/routed split observed by the cluster."""
    import ray_tpu
    from ray_tpu.util import metrics as metrics_mod

    cores = os.cpu_count() or 2
    if not n_drivers:
        n_drivers = 2 if SMOKE else max(2, min(8, cores * 2))
    if not calls_per_driver:
        calls_per_driver = 50 if SMOKE else 500

    @ray_tpu.remote(num_cpus=0.01)
    class Nop:
        def ping(self):
            return None

    @ray_tpu.remote(num_cpus=0.01)
    def driver(handle, k):
        import time as _t

        t0 = _t.perf_counter()
        ray_tpu.get([handle.ping.remote() for _ in range(k)], timeout=600)
        return _t.perf_counter() - t0

    actors = [Nop.remote() for _ in range(n_drivers)]
    ray_tpu.get([a.ping.remote() for a in actors], timeout=120)
    # pre-warm one driver worker per lane so the measured window isn't
    # worker cold-start
    ray_tpu.get([driver.remote(a, 2) for a in actors], timeout=120)
    t0 = time.perf_counter()
    outs = ray_tpu.get(
        [driver.remote(a, calls_per_driver) for a in actors], timeout=900)
    wall = time.perf_counter() - t0
    total = n_drivers * calls_per_driver
    for a in actors:
        ray_tpu.kill(a)  # release the leases for later bench phases
    return {
        "multi_driver_tasks_per_s": round(total / wall, 1),
        "multi_drivers": n_drivers,
        "multi_driver_wall_s": round(wall, 2),
        "multi_driver_slowest_s": round(max(outs), 2),
    }


def direct_actor_call_us(n: int = 300) -> dict:
    """Synchronous direct actor-call round trip (submit -> execute ->
    direct_result -> get) plus the pipelined direct rate, with the
    direct/routed counter split for the run."""
    import ray_tpu
    from ray_tpu.core.runtime import dispatch_counts

    @ray_tpu.remote
    class Counter:
        def __init__(self):
            self.n = 0

        def inc(self):
            self.n += 1
            return self.n

    c = Counter.remote()
    ray_tpu.get(c.inc.remote(), timeout=60)
    d0, r0 = dispatch_counts()
    t0 = time.perf_counter()
    for _ in range(n):
        ray_tpu.get(c.inc.remote(), timeout=60)
    rt_us = (time.perf_counter() - t0) / n * 1e6
    k = n * 4
    t0 = time.perf_counter()
    out = ray_tpu.get([c.inc.remote() for _ in range(k)], timeout=600)
    pipelined = k / (time.perf_counter() - t0)
    d1, r1 = dispatch_counts()
    assert out[-1] == 1 + n + k
    ray_tpu.kill(c)  # release the lease for later bench phases
    return {
        "direct_actor_call_us": round(rt_us, 1),
        "direct_actor_calls_per_s": round(pipelined, 1),
        "direct_calls": int(d1 - d0),
        "routed_calls": int(r1 - r0),
    }


def llm_serve_bench(n_requests: int = 0, concurrency: int = 8,
                    max_tokens: int = 0) -> dict:
    """LLM serving rows (ISSUE 7): continuous batching vs sequential
    per-request generation (acceptance: >= 3x aggregate tokens/s at
    concurrency >= 8), sustained-concurrency requests/s, and p50/p99
    TTFT / TPOT read back from the engine's metric histograms. Runs the
    engine in-process (it IS the replica's inner loop — the serve layer
    adds only routing) on jax's default backend."""
    from ray_tpu.serve.llm import EngineConfig, LLMEngine, build_model
    from ray_tpu.serve.llm.engine import _H_TPOT, _H_TTFT

    if not n_requests:
        n_requests = concurrency * (2 if SMOKE else 3)
    if not max_tokens:
        max_tokens = 16 if SMOKE else 32

    m, params = build_model("gpt-tiny")

    def mk(batch: int, name: str) -> LLMEngine:
        return LLMEngine(m, params, EngineConfig(
            max_batch=batch, num_blocks=max(64, concurrency * 8),
            block_size=8, max_blocks_per_seq=8, prefill_buckets=(8, 16),
            max_prefill_tokens_per_step=64), name=name)

    prompts = [[1 + (i % 50), 5, 9, 2] for i in range(n_requests)]

    # -- sequential baseline: one request at a time, batch-1 program ----
    seq_eng = mk(1, "bench-seq")
    s = seq_eng.add_request([1, 2, 3], max_tokens=2)
    seq_eng.run_until_idle(timeout=600)   # warmup: compile prefill+decode
    s.tokens()
    seq_tokens = 0
    t0 = time.perf_counter()
    for p in prompts:
        st = seq_eng.add_request(p, max_tokens=max_tokens)
        seq_eng.run_until_idle(timeout=600)
        seq_tokens += len(st.tokens())
    seq_dt = time.perf_counter() - t0
    seq_rate = seq_tokens / seq_dt

    # -- continuous batching: all clients at once, one shared program ---
    eng = mk(concurrency, "bench-llm")
    s = eng.add_request([1, 2, 3], max_tokens=2)
    eng.run_until_idle(timeout=600)       # warmup compile at this batch
    s.tokens()
    # the warmup's TTFT/TPOT samples carry XLA compile time under the
    # SAME engine tag; snapshot buckets so the reported percentiles are
    # the measured window's delta only
    tags = {"engine": "bench-llm"}

    def snap(h):
        with h._lock:
            return list(h._buckets.get(h._key(tags), ()))

    pre = {id(h): snap(h) for h in (_H_TTFT, _H_TPOT)}
    # drive the scheduler inline (tokens buffer in the per-request
    # streams; draining after the clock stops keeps client-thread GIL
    # noise out of the measured window — the streaming-client shape is
    # covered by scripts/llm_smoke.py and tests/test_llm_engine.py)
    t0 = time.perf_counter()
    streams = [eng.add_request(p, max_tokens=max_tokens) for p in prompts]
    eng.run_until_idle(timeout=900)
    wall = time.perf_counter() - t0
    total = sum(len(st.tokens(timeout=60)) for st in streams)
    eng.pool.check_leaks()
    rate = total / wall

    from ray_tpu.util.metrics import percentile_from_buckets

    def pct(h, p):
        post = snap(h)
        before = pre[id(h)] or [0] * len(post)
        delta = [b - a for a, b in zip(before, post)] if post else []
        v = percentile_from_buckets(h.boundaries, delta, p)
        return round(v * 1e3, 1) if v is not None else None

    return {
        "llm_seq_tokens_per_s": round(seq_rate, 1),
        "llm_batched_tokens_per_s": round(rate, 1),
        "llm_batching_speedup": round(rate / seq_rate, 2),
        "llm_requests_per_s": round(n_requests / wall, 2),
        "llm_concurrency": concurrency,
        "llm_max_tokens": max_tokens,
        "llm_ttft_p50_ms": pct(_H_TTFT, 50),
        "llm_ttft_p99_ms": pct(_H_TTFT, 99),
        "llm_tpot_p50_ms": pct(_H_TPOT, 50),
        "llm_tpot_p99_ms": pct(_H_TPOT, 99),
    }


def llm_trace_overhead_bench(concurrency: int = 8,
                             rounds: int = 3) -> dict:
    """Distributed-tracing A/B on the continuous-batching loop (ISSUE
    18 acceptance: per-request lifecycle spans — admit/prefill/decode
    aggregates/retire, plus exemplar-tagged TTFT/TPOT observes — must
    cost <= 3% tokens/s; requests WITHOUT a trace context must not pay
    at all, since every span site is gated on ``req.trace_ctx``).
    Interleaved traced/untraced rounds on one engine so compile state
    and box drift cancel; reports the median overhead."""
    import statistics

    from ray_tpu.serve.llm import EngineConfig, LLMEngine, build_model
    from ray_tpu.util import tracing

    n_requests = concurrency * (2 if SMOKE else 3)
    max_tokens = 16 if SMOKE else 32
    m, params = build_model("gpt-tiny")
    eng = LLMEngine(m, params, EngineConfig(
        max_batch=concurrency, num_blocks=max(64, concurrency * 8),
        block_size=8, max_blocks_per_seq=8, prefill_buckets=(8, 16),
        max_prefill_tokens_per_step=64), name="bench-trace")
    s = eng.add_request([1, 2, 3], max_tokens=2)
    eng.run_until_idle(timeout=600)       # warmup compile
    s.tokens()
    prompts = [[1 + (i % 50), 5, 9, 2] for i in range(n_requests)]

    def run(traced: bool) -> float:
        t0 = time.perf_counter()
        streams = [eng.add_request(
            p, max_tokens=max_tokens,
            trace_ctx=((tracing.new_trace_id(), tracing.new_span_id())
                       if traced else None)) for p in prompts]
        eng.run_until_idle(timeout=900)
        wall = time.perf_counter() - t0
        total = sum(len(st.tokens(timeout=60)) for st in streams)
        return total / wall

    run(True)                             # prime both paths
    ratios = []
    for _ in range(rounds):
        on = run(True)
        off = run(False)
        ratios.append(off / on)
    eng.pool.check_leaks()
    overhead_pct = (statistics.median(ratios) - 1.0) * 100
    rec = {"metric": "llm_trace_overhead_pct",
           "value": round(overhead_pct, 2), "unit": "%"}
    print(json.dumps(rec), flush=True)
    return {"llm_trace_overhead_pct": round(overhead_pct, 2),
            "llm_trace_overhead_rounds": [round(r, 4) for r in ratios]}


def prefix_cache_bench(prefix_len: int = 0, suffix_len: int = 32,
                       concurrency: int = 8, max_tokens: int = 8) -> dict:
    """Radix-prefix-cache rows (ISSUE 14 acceptance): ``concurrency``
    requests sharing one long common prefix with short unique suffixes,
    cache-off vs cache-on on the same engine shape. The cached run pays
    a block-table splice plus a suffix prefill where the cold run pays
    the full prompt — acceptance pins cached TTFT >= 3x better at the
    512-token prefix, outputs token-identical both ways."""
    import random as _random

    import jax.numpy as jnp

    from ray_tpu.serve.llm import EngineConfig, LLMEngine, build_model

    if not prefix_len:
        prefix_len = 128 if SMOKE else 512
    rng = _random.Random(0)
    prefix = [rng.randrange(1, 500) for _ in range(prefix_len)]
    suffixes = [[rng.randrange(1, 500) for _ in range(suffix_len)]
                for _ in range(concurrency)]
    ctx = prefix_len + suffix_len + max_tokens + 8
    m, params = build_model({"family": "gpt", "max_seq": ctx + 64,
                             "dtype": jnp.float32, "use_flash": False})
    bs = 16
    n_seq_blocks = (ctx // bs) + 2
    cfg = dict(block_size=bs,
               num_blocks=(concurrency + 2) * n_seq_blocks,
               max_batch=concurrency, max_blocks_per_seq=n_seq_blocks,
               prefill_buckets=(64, prefix_len + suffix_len + bs),
               max_prefill_tokens_per_step=prefix_len + suffix_len + bs)

    # per-request TTFT comes from the engine's own Request bookkeeping
    # (first_token_at - submitted_at); both runs carry the identical
    # workload, with one full-prefix seeding request each so the cached
    # run measures WARM-cache behaviour. The warmup requests compile
    # every program (cold prefill bucket, extend bucket, decode) before
    # the clock starts.
    def run_with_ttft(prefix_cache: bool):
        eng = LLMEngine(m, params, EngineConfig(prefix_cache=prefix_cache,
                                                **cfg))
        for warm in ([prefix[:48]] if not prefix_cache
                     else [prefix[:48], prefix[:40] + [7] * 8]):
            st = eng.add_request(warm, max_tokens=2)
            eng.run_until_idle(timeout=900)
            st.tokens()
        st = eng.add_request(prefix + suffixes[0][:1], max_tokens=2)
        eng.run_until_idle(timeout=900)
        st.tokens()
        t0 = time.perf_counter()
        streams = [eng.add_request(prefix + sfx, max_tokens=max_tokens)
                   for sfx in suffixes]
        reqs = list(eng._waiting)
        eng.run_until_idle(timeout=900)
        wall = time.perf_counter() - t0
        outs = [st.tokens(timeout=60) for st in streams]
        ttfts = sorted(r.first_token_at - r.submitted_at for r in reqs)
        eng.pool.check_leaks()
        stats = eng.cache_stats() if prefix_cache else {}
        return outs, wall, ttfts, stats

    cold_outs, cold_wall, cold_ttfts, _ = run_with_ttft(False)
    outs, wall, ttfts, stats = run_with_ttft(True)

    def p50(v):
        return v[len(v) // 2]

    cold_ms = round(p50(cold_ttfts) * 1e3, 1)
    cached_ms = round(p50(ttfts) * 1e3, 1)
    return {
        "llm_prefix_len": prefix_len,
        "llm_ttft_ms_cold": cold_ms,
        "llm_ttft_ms_cached": cached_ms,
        "llm_ttft_prefix_speedup": round(cold_ms / max(cached_ms, 1e-3), 2),
        "llm_prefix_wall_speedup": round(cold_wall / max(wall, 1e-6), 2),
        "prefix_hit_rate": stats.get("cache_hit_rate", 0.0),
        "prefix_tokens_identical": outs == cold_outs,
    }


def _pipeline_mlp(num_chunks: int, width: int, M: int, mb_size: int = 2):
    """Compute-light tanh-MLP pipeline fixture (the ISSUE 8 acceptance
    config measures ENGINE overhead, not matmul time)."""
    import jax
    import jax.numpy as jnp

    k = jax.random.PRNGKey(0)

    def mk_mid():
        def fn(p, x):
            return jnp.tanh(x @ p["w"] + p["b"])
        return fn

    def mk_last():
        def fn(p, x, targets):
            return jnp.mean((x @ p["w"] + p["b"] - targets) ** 2)
        return fn

    fns = [mk_mid() for _ in range(num_chunks - 1)] + [mk_last()]
    params = [
        {"w": jax.random.normal(jax.random.fold_in(k, i),
                                (width, width)) * 0.3,
         "b": jnp.zeros((width,))}
        for i in range(num_chunks)]
    xs = jax.random.normal(jax.random.fold_in(k, 91), (M * mb_size, width))
    ys = jax.random.normal(jax.random.fold_in(k, 92), (M * mb_size, width))
    mbs = [xs[i * mb_size:(i + 1) * mb_size] for i in range(M)]
    tgts = [ys[i * mb_size:(i + 1) * mb_size] for i in range(M)]
    return fns, params, mbs, tgts


def _timed_steps(eng, mbs, tgts, warmup: int, timed: int) -> float:
    """Mean steady-state step seconds (warmup covers compile + channel
    prime)."""
    for _ in range(warmup):
        eng.step(mbs, tgts)
    t0 = time.perf_counter()
    for _ in range(timed):
        eng.step(mbs, tgts)
    return (time.perf_counter() - t0) / timed


def pipeline_train_bench() -> dict:
    """Pipeline-engine rows (ISSUE 8). Assumes an initialized cluster.

    - ``pipeline_vs_remote_speedup``: steady-state step time of the
      compiled-graph engine vs the dynamic ``.remote()`` engine at the
      acceptance config (2 stages x 8 microbatches, compute-light MLP so
      per-microbatch dispatch is what's measured).
    - ``pipeline_train_tokens_per_s``: GPT-tiny 2-stage 1F1B throughput
      on the compiled engine (real tokens; the old engine re-traces
      ``jax.vjp`` per microbatch on GPT and is benched at the MLP config
      only — docs/PERF_NOTES.md round 7).
    - ``zero_update_ms`` vs ``replicated_update_ms``: dp=2 update-phase
      time and per-replica optimizer-state bytes from the stage reports
      (adam, single-stage pure-dp engine).
    """
    import optax

    from ray_tpu.train.pipeline_cgraph import CompiledPipelineEngine
    from ray_tpu.train.pipeline_engine import PipelineEngine

    warmup, timed = (1, 2) if SMOKE else (2, 4)
    out: dict = {}

    # -- old vs new at the acceptance config ------------------------------
    M = 4 if SMOKE else 8
    fns, params, mbs, tgts = _pipeline_mlp(2, 32, M)
    tx = optax.sgd(1e-2)
    old = PipelineEngine(fns, params, tx=tx)
    try:
        old_s = _timed_steps(old, mbs, tgts, warmup, timed)
    finally:
        old.shutdown()
    new = CompiledPipelineEngine(fns, params, tx, num_microbatches=M,
                                 channel_bytes=1 << 18)
    try:
        new_s = _timed_steps(new, mbs, tgts, warmup, timed)
    finally:
        new.shutdown()
    out["pipeline_remote_step_ms"] = round(old_s * 1e3, 2)
    out["pipeline_cgraph_step_ms"] = round(new_s * 1e3, 2)
    out["pipeline_vs_remote_speedup"] = round(old_s / new_s, 2)
    out["pipeline_stages"] = 2
    out["pipeline_microbatches"] = M

    # -- GPT-tiny tokens/s through the compiled engine --------------------
    try:
        import jax
        import jax.numpy as jnp

        from ray_tpu.models import GPT, GPTConfig
        from ray_tpu.models.gpt import gpt_pipeline_stages

        cfg = GPTConfig.tiny(dtype=jnp.float32, use_flash=False,
                             scan_layers=True)
        model = GPT(cfg)
        gparams = jax.jit(model.init)(jax.random.PRNGKey(0))
        stage_fns, stage_params, tied = gpt_pipeline_stages(model, gparams, 2)
        gM, batch, seq = (2, 2, 64) if SMOKE else (8, 2, 128)
        tokens = jax.random.randint(jax.random.PRNGKey(1),
                                    (gM * batch, seq), 0, cfg.vocab_size)
        targets = jnp.roll(tokens, -1, axis=1)
        gmbs = [tokens[i * batch:(i + 1) * batch] for i in range(gM)]
        gtgts = [targets[i * batch:(i + 1) * batch] for i in range(gM)]
        geng = CompiledPipelineEngine(stage_fns, stage_params,
                                      optax.adam(1e-3), num_microbatches=gM,
                                      tied=tied, channel_bytes=1 << 20)
        try:
            gpt_s = _timed_steps(geng, gmbs, gtgts, warmup, timed)
        finally:
            geng.shutdown()
        out["pipeline_train_tokens_per_s"] = round(gM * batch * seq / gpt_s, 1)
        out["pipeline_gpt_step_ms"] = round(gpt_s * 1e3, 2)
        out["pipeline_gpt_tokens_per_step"] = gM * batch * seq
    except Exception:
        import traceback

        traceback.print_exc()  # a broken GPT split must not zero the row

    # -- ZeRO-sharded vs replicated dp=2 update ---------------------------
    def dp_engine(zero: bool):
        zfns, zparams, zmbs, ztgts = _pipeline_mlp(
            1, 16 if SMOKE else 128, 2)
        eng = CompiledPipelineEngine(
            [zfns[-1]], [zparams[-1]], optax.adam(1e-3),
            num_microbatches=2, dp=2, zero_update=zero,
            channel_bytes=1 << 18)
        try:
            _timed_steps(eng, zmbs + zmbs, ztgts + ztgts, warmup, timed)
            upd_ms = [r["update_ms"] for r in eng.last_reports]
            opt_bytes = [r["opt_state_bytes"] for r in eng.last_reports]
        finally:
            eng.shutdown()
        return round(max(upd_ms), 3), max(opt_bytes)

    try:
        zero_ms, zero_bytes = dp_engine(True)
        repl_ms, repl_bytes = dp_engine(False)
        out["zero_update_ms"] = zero_ms
        out["replicated_update_ms"] = repl_ms
        out["zero_opt_state_bytes_per_replica"] = zero_bytes
        out["replicated_opt_state_bytes_per_replica"] = repl_bytes
    except Exception:
        import traceback

        traceback.print_exc()
    return out


def data_plane_bench() -> dict:
    """Streaming data-plane rows (ISSUE 19, docs/DATA.md). Assumes an
    initialized cluster.

    - ``data_ingest_mb_s``: MB/s through a from_numpy->map_batches
      streaming plan with the byte budget ON (~8 blocks worth), wall
      clock over the block bytes drained at the consumer.
    - ``shuffle_epoch_ms``: wall clock to drain one ``windowed_shuffle``
      epoch end-to-end on the same block population — the streaming-
      shuffle latency a training epoch pays.
    - ``feed_vs_handfed_tokens_ratio``: steady-state step time of a
      hand-fed ``CompiledPipelineEngine`` over the SAME engine config
      fed the identical microbatches through ``attach_feed`` pump
      actors. >= 0.95 is the acceptance bar (scripts/data_smoke.py
      asserts it): the pump tier must keep the rings at least as
      resident as the driver's synchronous sends.
    """
    import optax

    import ray_tpu.data as rd
    from ray_tpu.data import DataContext, DataFeed
    from ray_tpu.train.pipeline_cgraph import CompiledPipelineEngine

    out: dict = {}

    # -- ingest MB/s, byte budget on --------------------------------------
    rows, width, P = (4096, 64, 8) if SMOKE else (65536, 256, 32)
    x = np.random.default_rng(0).standard_normal(
        (rows, width)).astype(np.float32)
    ctx = DataContext.get_current()
    old_budget = ctx.target_max_bytes_inflight
    ctx.target_max_bytes_inflight = 8 * (x.nbytes // P)
    try:
        t0 = time.perf_counter()
        ds = rd.from_numpy({"x": x}, parallelism=P).map_batches(
            lambda b: {"x": np.tanh(b["x"])})
        total = 0
        for b in ds.iter_batches(batch_size=None):
            total += b["x"].nbytes
        dt = time.perf_counter() - t0
    finally:
        ctx.target_max_bytes_inflight = old_budget
    assert total == x.nbytes, f"drained {total} of {x.nbytes} bytes"
    out["data_ingest_mb_s"] = round(total / dt / 1e6, 1)
    out["data_ingest_blocks"] = P
    out["data_ingest_peak_bytes_inflight"] = \
        ds.stats().get("peak_bytes_inflight", 0)

    # -- windowed-shuffle epoch drain -------------------------------------
    t0 = time.perf_counter()
    sds = rd.from_numpy({"x": x}, parallelism=P).windowed_shuffle(
        window_blocks=4, seed=11)
    n = 0
    for b in sds.iter_batches(batch_size=None):
        n += len(b["x"])
    out["shuffle_epoch_ms"] = round((time.perf_counter() - t0) * 1e3, 1)
    assert n == rows, f"shuffle epoch drained {n} of {rows} rows"

    # -- feed-fed vs hand-fed engine throughput ---------------------------
    # compute-meaningful microbatches (64 rows x 128 wide) so the row
    # measures starvation, not channel-poll jitter; MEDIAN step time on
    # both sides for the same reason (CI runs on oversubscribed cores)
    M = 4
    warmup, timed = (2, 6) if SMOKE else (3, 12)
    fns, params, mbs, tgts = _pipeline_mlp(2, 128, M, mb_size=64)
    tx = optax.sgd(1e-2)

    def _median_steps(eng, step):
        for _ in range(warmup):
            step()
        ts = []
        for _ in range(timed):
            t0 = time.perf_counter()
            step()
            ts.append(time.perf_counter() - t0)
        ts.sort()
        return ts[len(ts) // 2]

    eng = CompiledPipelineEngine(fns, params, tx, num_microbatches=M,
                                 channel_bytes=1 << 20)
    try:
        hand_s = _median_steps(eng, lambda: eng.step(mbs, tgts))
    finally:
        eng.shutdown()

    nmbs = [np.asarray(v) for v in mbs]
    ntgts = [np.asarray(v) for v in tgts]
    steps_total = warmup + timed + 4

    def factory():
        def it():
            for _ in range(steps_total):
                for xx, tt in zip(nmbs, ntgts):
                    yield xx, tt
        return it()

    feng = CompiledPipelineEngine(fns, params, tx, num_microbatches=M,
                                  channel_bytes=1 << 20)
    try:
        feng.attach_feed(DataFeed([factory]))
        fed_s = _median_steps(feng, lambda: feng.step())
    finally:
        feng.shutdown()
    tokens_per_step = M * nmbs[0].shape[0]
    out["data_handfed_tokens_per_s"] = round(tokens_per_step / hand_s, 1)
    out["data_fed_tokens_per_s"] = round(tokens_per_step / fed_s, 1)
    out["feed_vs_handfed_tokens_ratio"] = round(hand_s / fed_s, 3)
    return out


def perf_overhead_bench() -> dict:
    """Observability rows (ISSUE 17). Assumes an initialized cluster.

    - ``profiler_overhead_pct``: steady-state step-time delta with the
      flight recorder on (the always-on default) vs off — toggled on the
      driver AND every stage worker via ``set_flight_recording`` — at
      the ISSUE 8 acceptance config (2 stages x 8 microbatches,
      compute-light MLP so event cost is maximally visible). The
      acceptance bar is <= 3% on a quiet box.
    - ``pipeline_bubble_frac``: measured bubble fraction from
      ``CompiledPipelineEngine.profile()``, next to the 1F1B analytic
      value (P-1)/(M+P-1) for the same config.
    """
    import optax

    from ray_tpu.perf import analytic_bubble_frac
    from ray_tpu.train.pipeline_cgraph import CompiledPipelineEngine

    warmup, timed = (1, 3) if SMOKE else (2, 8)
    M = 4 if SMOKE else 8
    fns, params, mbs, tgts = _pipeline_mlp(2, 32, M)
    out: dict = {}
    eng = CompiledPipelineEngine(fns, params, optax.sgd(1e-2),
                                 num_microbatches=M, channel_bytes=1 << 18)
    try:
        on_s = _timed_steps(eng, mbs, tgts, warmup, timed)
        eng.set_flight_recording(False)
        try:
            off_s = _timed_steps(eng, mbs, tgts, 1, timed)
        finally:
            eng.set_flight_recording(True)
        out["pipeline_step_ms_recorder_on"] = round(on_s * 1e3, 2)
        out["pipeline_step_ms_recorder_off"] = round(off_s * 1e3, 2)
        out["profiler_overhead_pct"] = round((on_s - off_s) / off_s * 100, 2)
        rep = eng.profile(steps=2 if SMOKE else 4)
        out["pipeline_bubble_frac"] = round(rep.bubble_frac, 4)
        out["pipeline_bubble_frac_analytic"] = round(
            analytic_bubble_frac(2, M), 4)
        out["profile_step_ms"] = round(rep.mean_step_ms, 2)
        out["profile_phase_wall_ratio"] = round(rep.phase_wall_ratio(), 3)
    finally:
        eng.shutdown()

    # -- llm tokens/s A/B (in-process engine, so set_enabled covers its
    # whole event surface; driven inline like llm_serve_bench) ----------
    try:
        from ray_tpu.perf import set_enabled
        from ray_tpu.serve.llm import EngineConfig, LLMEngine, build_model

        m, params = build_model("gpt-tiny")
        conc = 4 if SMOKE else 8
        leng = LLMEngine(m, params, EngineConfig(
            max_batch=conc, num_blocks=64, block_size=8,
            max_blocks_per_seq=8, prefill_buckets=(8, 16),
            max_prefill_tokens_per_step=64), name="bench-perf")
        st = leng.add_request([1, 2, 3], max_tokens=2)
        leng.run_until_idle(timeout=600)   # warmup: compile prefill+decode
        st.tokens()
        max_tokens = 8 if SMOKE else 16

        def llm_rate() -> float:
            prompts = [[1 + (i % 50), 5, 9, 2] for i in range(conc * 2)]
            t0 = time.perf_counter()
            streams = [leng.add_request(p, max_tokens=max_tokens)
                       for p in prompts]
            leng.run_until_idle(timeout=600)
            total = sum(len(s.tokens(timeout=60)) for s in streams)
            return total / (time.perf_counter() - t0)

        on_r = llm_rate()
        set_enabled(False)
        try:
            off_r = llm_rate()
        finally:
            set_enabled(True)
        out["llm_tokens_per_s_recorder_on"] = round(on_r, 1)
        out["llm_tokens_per_s_recorder_off"] = round(off_r, 1)
        out["llm_profiler_overhead_pct"] = round(
            (off_r - on_r) / off_r * 100, 2)
    except Exception:
        import traceback

        traceback.print_exc()
    return out


class _CodecRank:
    """One rank of the codec bench's dp=2 host-collective group: runs
    the full ZeRO sync (reduce-scatter + shard update + all-gather)
    over a fixed-size flat parameter vector, with or without a wire
    codec, and reports wall time + the bytes its contributions put on
    the wire."""

    def __init__(self, rank: int, n: int, group: str):
        import jax.numpy as jnp
        import optax

        from ray_tpu.parallel import collective
        from ray_tpu.parallel.zero import ZeroUpdater

        collective.create_collective_group(2, rank, group_name=group)
        self._rank = rank
        self._n = n
        self._group = group
        self._params = {"w": jnp.linspace(-1.0, 1.0, n,
                                          dtype=jnp.float32)}
        self._grads = {"w": jnp.linspace(1.0, -1.0, n,
                                         dtype=jnp.float32)}
        self._tx = optax.adam(1e-3)
        self._ZeroUpdater = ZeroUpdater

    def sync(self, codec, warmup: int, timed: int) -> dict:
        import time as _t

        import numpy as np

        from ray_tpu.parallel import quant

        z = self._ZeroUpdater(self._tx, 2, self._rank,
                              group_name=self._group, grad_codec=codec)
        z.init(self._params)
        params = self._params
        for _ in range(warmup):
            params = z.update(params, self._grads)
        t0 = _t.perf_counter()
        for _ in range(timed):
            params = z.update(params, self._grads)
        ms = (_t.perf_counter() - t0) / timed * 1e3
        vec = np.zeros((self._n,), np.float32)
        leg = quant.quantize(vec, codec).nbytes() if codec \
            else vec.nbytes
        # one sync = grad reduce-scatter (full vector out) + param
        # all-gather (1/dp shard out) per rank
        shard = np.zeros((self._n // 2,), np.float32)
        leg2 = quant.quantize(shard, codec).nbytes() if codec \
            else shard.nbytes
        return {"ms": round(ms, 3), "bytes": int(leg + leg2)}


def collective_codec_bench() -> dict:
    """Quantized-collective rows (ISSUE 13, docs/COLLECTIVES.md bench
    methodology). Assumes an initialized cluster.

    - ``zero_sync_ms_{fp32,int8}`` + ``bytes_moved_{fp32,int8}``: one
      full ZeRO dp=2 sync (reduce-scatter + shard adam + all-gather)
      over a fixed 1M-param fp32 vector on the host-collective plane;
      bytes are the per-rank wire contribution per step (int8 payload
      + per-block scales ~25.4% of fp32 — the <= 30% acceptance bar).
      On this CPU sandbox the rendezvous-store round trip dominates
      the sync time, so the ms win is modest here; the bytes column is
      the DCN story.
    - ``disagg_kv_ms_{raw,codec}``: one prefill->decode generate()
      through the disagg cgraph channel with the KV shipment raw vs
      int8-quantized (token-identical on gpt-tiny — pinned in
      tests/test_collective_codec.py).
    """
    import ray_tpu

    out: dict = {}
    n = (1 << 18) if SMOKE else (1 << 20)
    warmup, timed = (1, 2) if SMOKE else (2, 5)
    R = ray_tpu.remote(_CodecRank)
    try:
        ranks = [R.remote(r, n, "codec-bench") for r in (0, 1)]
        for codec, tag in ((None, "fp32"), ("int8", "int8")):
            rows = ray_tpu.get(
                [a.sync.remote(codec, warmup, timed) for a in ranks],
                timeout=300)
            out[f"zero_sync_ms_{tag}"] = max(r["ms"] for r in rows)
            out[f"bytes_moved_{tag}"] = rows[0]["bytes"]
        out["zero_sync_bytes_ratio"] = round(
            out["bytes_moved_int8"] / out["bytes_moved_fp32"], 4)
        for a in ranks:
            ray_tpu.kill(a)
    except Exception:
        import traceback

        traceback.print_exc()  # a broken sync must not look like 0
    try:
        from ray_tpu.serve.llm.disagg import DisaggLLM

        reps = 2 if SMOKE else 4
        for codec, tag in ((None, "raw"), ("int8", "codec")):
            llm = DisaggLLM(model="gpt-tiny", codec=codec)
            try:
                llm.generate([1, 5, 9], max_tokens=8)  # compile warmup
                t0 = time.perf_counter()
                for _ in range(reps):
                    llm.generate([1, 5, 9], max_tokens=8)
                out[f"disagg_kv_ms_{tag}"] = round(
                    (time.perf_counter() - t0) / reps * 1e3, 2)
            finally:
                llm.shutdown()
    except Exception:
        import traceback

        traceback.print_exc()
    return out


def sharding_bench() -> dict:
    """Sharded-execution rows (ISSUE 11, docs/SHARDING.md bench
    methodology). MUST run in a process whose XLA_FLAGS forced >= 4
    host devices BEFORE jax import (bench.py spawns one; `python
    bench_core.py --sharding-json` is the entry point).

    - ``llm_tokens_per_s_tp{1,2,4}``: gpt-tiny engine decode
      throughput under the tp mesh, token-identity asserted against
      tp=1 (the acceptance bar rides along with the number).
    - ``pipeline_step_ms_fsdp{1,2}``: 2-stage MLP 1F1B step time with
      the stage params/opt-state on the fsdp plane, loss bitwise
      against fsdp=1.

    On the CPU verification backend tp/fsdp ADD work (the collectives
    are real, the chips aren't), so these rows pin the *overhead* of
    the sharded lowering, not a speedup — the speedup story needs ICI
    (MULTICHIP dryruns).
    """
    import jax

    from ray_tpu.serve.llm import EngineConfig, LLMEngine, build_model

    out: dict = {}
    n_dev = len(jax.devices())
    widths = [w for w in (1, 2, 4) if w <= n_dev]
    m, params = build_model("gpt-tiny")
    prompts = [[1 + (i % 50), 5, 9, 2] for i in range(8)]
    max_tokens = 16 if SMOKE else 32
    base_tokens = None
    for tp in widths:
        eng = LLMEngine(m, params, EngineConfig(
            max_batch=4, num_blocks=64, block_size=8,
            max_blocks_per_seq=8, prefill_buckets=(8,), tp=tp),
            name=f"bench-tp{tp}")
        s = eng.add_request([1, 2, 3], max_tokens=2)
        eng.run_until_idle(timeout=600)     # compile warmup
        s.tokens()
        t0 = time.perf_counter()
        streams = [eng.add_request(p, max_tokens=max_tokens)
                   for p in prompts]
        eng.run_until_idle(timeout=900)
        dt = time.perf_counter() - t0
        toks = [st.tokens(timeout=60) for st in streams]
        eng.pool.check_leaks()
        out[f"llm_tokens_per_s_tp{tp}"] = round(
            sum(len(t) for t in toks) / dt, 1)
        if tp == 1:
            base_tokens = toks
        else:
            out[f"llm_tp{tp}_token_identical"] = toks == base_tokens

    # -- fsdp pipeline step time ------------------------------------------
    import optax

    import ray_tpu
    from ray_tpu.train.pipeline_cgraph import CompiledPipelineEngine

    ray_tpu.init(num_cpus=max(4, os.cpu_count() or 4),
                 ignore_reinit_error=True)
    fns, params, mbs, tgts = _pipeline_mlp(2, 64, 4)
    warmup, timed = (1, 2) if SMOKE else (2, 4)
    base_loss = None
    for fsdp in [w for w in (1, 2) if w <= n_dev]:
        eng = CompiledPipelineEngine(fns, params, optax.adam(1e-3),
                                     num_microbatches=4, fsdp=fsdp,
                                     channel_bytes=1 << 18)
        try:
            for _ in range(warmup):
                loss = eng.step(mbs, tgts)
            t0 = time.perf_counter()
            for _ in range(timed):
                loss = eng.step(mbs, tgts)
            step_s = (time.perf_counter() - t0) / timed
            if eng.last_reports and fsdp > 1:
                out["fsdp_bytes_per_chip"] = \
                    eng.last_reports[0].get("fsdp_bytes_per_chip")
        finally:
            eng.shutdown()
        out[f"pipeline_step_ms_fsdp{fsdp}"] = round(step_s * 1e3, 2)
        if fsdp == 1:
            base_loss = loss
        else:
            out[f"pipeline_fsdp{fsdp}_loss_bitwise"] = loss == base_loss
    ray_tpu.shutdown()
    return out


def main() -> int:
    import ray_tpu

    rt = ray_tpu.init(num_cpus=max(4, os.cpu_count() or 4))
    results = []
    n_small = 100 if SMOKE else 2000
    n_calls = 100 if SMOKE else 3000
    n_puts = 20 if SMOKE else 200

    @ray_tpu.remote
    def nop():
        return None

    @ray_tpu.remote
    def many_returns():
        return tuple(range(64))

    @ray_tpu.remote
    def sink(*args):
        return len(args)

    @ray_tpu.remote
    class Counter:
        def __init__(self):
            self.n = 0

        def inc(self):
            self.n += 1
            return self.n

    # warmup: spin up workers + export functions
    ray_tpu.get([nop.remote() for _ in range(8)], timeout=120)

    # -- tasks/s (single submitter, pipelined) ------------------------------
    t0 = time.perf_counter()
    ray_tpu.get([nop.remote() for _ in range(n_small)], timeout=600)
    results.append(_rate("tasks_per_second", n_small,
                         time.perf_counter() - t0, "tasks/s"))

    # -- actor calls/s (pipelined on one actor) -----------------------------
    c = Counter.remote()
    ray_tpu.get(c.inc.remote(), timeout=60)
    t0 = time.perf_counter()
    out = ray_tpu.get([c.inc.remote() for _ in range(n_calls)], timeout=600)
    assert out[-1] == n_calls + 1
    results.append(_rate("actor_calls_per_second", n_calls,
                         time.perf_counter() - t0, "calls/s"))

    # -- sync actor call latency (round-trip) -------------------------------
    t0 = time.perf_counter()
    for _ in range(n_small // 10):
        ray_tpu.get(c.inc.remote(), timeout=60)
    dt = time.perf_counter() - t0
    rec = {"metric": "actor_call_round_trip_ms",
           "value": round(1000 * dt / (n_small // 10), 3), "unit": "ms"}
    print(json.dumps(rec), flush=True)
    results.append(rec)

    # -- put throughput (1 MiB objects) -------------------------------------
    blob = np.random.default_rng(0).integers(
        0, 255, size=1024 * 1024, dtype=np.uint8)
    t0 = time.perf_counter()
    refs = [ray_tpu.put(blob) for _ in range(n_puts)]
    dt = time.perf_counter() - t0
    results.append(_rate("put_gigabytes_per_second",
                         n_puts * blob.nbytes / 1e9, dt, "GB/s"))

    # -- get throughput (zero-copy reads of those puts) ---------------------
    t0 = time.perf_counter()
    vals = ray_tpu.get(refs, timeout=300)
    dt = time.perf_counter() - t0
    assert len(vals) == n_puts
    results.append(_rate("get_gigabytes_per_second",
                         n_puts * blob.nbytes / 1e9, dt, "GB/s"))
    del vals, refs

    # -- many args to one task (ref envelope: 10k+) -------------------------
    n_args = 100 if SMOKE else 1000
    arg_refs = [ray_tpu.put(i) for i in range(n_args)]
    t0 = time.perf_counter()
    assert ray_tpu.get(sink.remote(*arg_refs), timeout=300) == n_args
    rec = {"metric": "args_per_task", "value": n_args,
           "unit": f"args in {round(time.perf_counter() - t0, 2)}s"}
    print(json.dumps(rec), flush=True)
    results.append(rec)

    # -- many returns -------------------------------------------------------
    t0 = time.perf_counter()
    refs = many_returns.options(num_returns=64).remote()
    vals = ray_tpu.get(list(refs), timeout=120)
    assert vals == list(range(64))
    rec = {"metric": "returns_per_task", "value": 64,
           "unit": f"returns in {round(time.perf_counter() - t0, 2)}s"}
    print(json.dumps(rec), flush=True)
    results.append(rec)

    # -- direct dispatch (ISSUE 6): round trip + multi-driver envelope ------
    direct = direct_actor_call_us(50 if SMOKE else 300)
    for name in ("direct_actor_call_us", "direct_actor_calls_per_s"):
        rec = {"metric": name, "value": direct[name],
               "unit": "us" if name.endswith("_us") else "calls/s"}
        print(json.dumps(rec), flush=True)
        results.append(rec)
    print(json.dumps({"metric": "dispatch_split",
                      "value": {"direct": direct["direct_calls"],
                                "routed": direct["routed_calls"]}}),
          flush=True)
    md = multi_driver_tasks_per_s()
    rec = {"metric": "multi_driver_tasks_per_s",
           "value": md["multi_driver_tasks_per_s"],
           "unit": f"tasks/s aggregate over {md['multi_drivers']} drivers"}
    print(json.dumps(rec), flush=True)
    results.append(rec)

    # -- compiled graph vs .remote() chain (ISSUE 4: >= 5x) -----------------
    chain = chain_roundtrip_us(50 if SMOKE else 300)
    for name in ("remote_chain_roundtrip_us", "cgraph_chain_roundtrip_us"):
        rec = {"metric": name, "value": chain[name], "unit": "us"}
        print(json.dumps(rec), flush=True)
        results.append(rec)
    rec = {"metric": "cgraph_speedup", "value": chain["cgraph_speedup"],
           "unit": "x"}
    print(json.dumps(rec), flush=True)
    results.append(rec)

    # -- LLM serving (ISSUE 7: continuous batching >= 3x sequential) --------
    llm = llm_serve_bench(concurrency=4 if SMOKE else 8)
    for name in ("llm_seq_tokens_per_s", "llm_batched_tokens_per_s",
                 "llm_batching_speedup", "llm_requests_per_s"):
        rec = {"metric": name, "value": llm[name],
               "unit": "x" if name.endswith("speedup") else
               ("req/s" if "requests" in name else "tokens/s")}
        print(json.dumps(rec), flush=True)
        results.append(rec)
    print(json.dumps({"metric": "llm_latency_ms",
                      "value": {k: llm[k] for k in
                                ("llm_ttft_p50_ms", "llm_ttft_p99_ms",
                                 "llm_tpot_p50_ms", "llm_tpot_p99_ms")}}),
          flush=True)

    # -- pipeline training engine (ISSUE 8: cgraph vs .remote(), ZeRO) ------
    ray_tpu.kill(c)  # release the Counter lease: the engines' placement
    # groups need the CPUs, and a starved box skews the A/B step times
    pipe = pipeline_train_bench()
    for name in ("pipeline_train_tokens_per_s", "pipeline_vs_remote_speedup",
                 "zero_update_ms"):
        if name in pipe:
            rec = {"metric": name, "value": pipe[name],
                   "unit": ("x" if name.endswith("speedup") else
                            "ms" if name.endswith("_ms") else "tokens/s")}
            print(json.dumps(rec), flush=True)
            results.append(rec)
    print(json.dumps({"metric": "pipeline_detail", "value": pipe}),
          flush=True)

    ray_tpu.shutdown()
    print(json.dumps({"metric": "core_microbench_summary",
                      "value": {r["metric"]: r["value"] for r in results},
                      "smoke": SMOKE}), flush=True)
    return 0


if __name__ == "__main__":
    if "--sharding-json" in sys.argv:
        # bench.py subprocess entry: the parent seeded XLA_FLAGS with
        # forced host devices before this interpreter imported jax
        print("SHARDING_JSON:" + json.dumps(sharding_bench()), flush=True)
        sys.exit(0)
    sys.exit(main())
