"""Fused device-resident PPO (the Podracer/"Anakin" layout): env,
rollout, GAE, and SGD compile into ONE XLA program per dispatch — the
pipeline that runs the pixels benchmark at ~160k env-steps/s on a
single v5e chip (vs ~100-500/s for any host-rollout design over a slow
host<->device link). See docs/PERF_NOTES.md round 5.

Usage:
    python examples/ppo_jax_fused.py                   # CartPole
    python examples/ppo_jax_fused.py --env BreakoutShaped-v0 --hidden 512
"""
import argparse
import time


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--env", default="CartPole-v1",
                    choices=["CartPole-v1", "BreakoutShaped-v0"])
    ap.add_argument("--num-envs", type=int, default=64)
    ap.add_argument("--rollout-len", type=int, default=64)
    ap.add_argument("--iters-per-step", type=int, default=4)
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--hidden", type=int, default=64)
    ap.add_argument("--epochs", type=int, default=4)
    args = ap.parse_args()

    from ray_tpu.rllib import PPOJaxConfig

    algo = PPOJaxConfig(
        env=args.env, num_envs=args.num_envs,
        rollout_len=args.rollout_len, iters_per_step=args.iters_per_step,
        sgd_minibatch_size=min(1024, args.num_envs * args.rollout_len),
        num_sgd_epochs=args.epochs,
        hidden=(args.hidden,) if args.env.startswith("Breakout")
        else (args.hidden, args.hidden)).build()
    t0 = time.time()
    for i in range(args.steps):
        r = algo.train()
        if i % 5 == 0 or i == args.steps - 1:
            print(f"[{i:3d}] reward={r['episode_reward_mean']:8.2f} "
                  f"steps/s={r['env_steps_per_sec']:>9.0f} "
                  f"total={r['timesteps_total']}")
    print(f"done: {r['timesteps_total']} env steps in "
          f"{time.time() - t0:.1f}s")


if __name__ == "__main__":
    main()
