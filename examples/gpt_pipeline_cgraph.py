"""Pipeline-parallel GPT forward pass on compiled graphs (ISSUE 4 demo).

The MPMD shape compiled graphs exist for (arxiv 2412.14374): the
transformer stack is split into N stage actors, each holding its layer
slice resident; a compiled graph wires them driver -> stage0 -> ... ->
stageN-1 -> driver through pre-allocated channels, and the driver keeps
`depth` batches in flight so every stage computes every tick — sustained
pipeline throughput with zero per-hop scheduling or task-spec traffic.

Run: python examples/gpt_pipeline_cgraph.py [--stages 2] [--iters 20]
(CPU-friendly tiny config by default; scale --layers/--d-model on TPU.)
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

import ray_tpu  # noqa: E402
from ray_tpu.cgraph import InputNode  # noqa: E402


@ray_tpu.remote
class GPTStage:
    """One pipeline stage: a contiguous slice of the transformer stack.
    Stage 0 owns the embedding; the last stage owns the final layernorm
    and LM head. All stages init the same seeded params and keep only
    their slice — no parameter shipping at runtime."""

    def __init__(self, cfg_kw: dict, stage_idx: int, num_stages: int,
                 seed: int = 0):
        import jax
        import jax.numpy as jnp

        from ray_tpu.models.gpt import GPT, GPTConfig
        from ray_tpu.ops import layernorm

        cfg = GPTConfig(dtype=jnp.float32, use_flash=False, remat=False,
                        **cfg_kw)
        model = GPT(cfg)
        params = jax.jit(model.init)(jax.random.PRNGKey(seed))
        L = cfg.n_layer
        per = L // num_stages
        lo = stage_idx * per
        hi = L if stage_idx == num_stages - 1 else lo + per
        head_keys = ("wte", "wpe", "lnf_g", "lnf_b")
        lp = {k: v[lo:hi] for k, v in params.items() if k not in head_keys}
        first = stage_idx == 0
        last = stage_idx == num_stages - 1
        wte, wpe = params["wte"], params["wpe"]
        lnf_g, lnf_b = params["lnf_g"], params["lnf_b"]

        def fwd(x):
            if first:
                x = model._embed(wte, wpe, x)
            for i in range(hi - lo):
                x = model._block(x, {k: v[i] for k, v in lp.items()}, None)
            if last:
                x = layernorm(x, lnf_g, lnf_b)
                return model._lm_head(wte, x)
            return x

        self._fwd = jax.jit(fwd)
        self._jnp = jnp

    def fwd(self, x):
        return np.asarray(self._fwd(self._jnp.asarray(x)))


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--stages", type=int, default=2)
    p.add_argument("--iters", type=int, default=20)
    p.add_argument("--layers", type=int, default=4)
    p.add_argument("--d-model", type=int, default=64)
    p.add_argument("--batch", type=int, default=4)
    p.add_argument("--seq", type=int, default=128)
    args = p.parse_args()
    assert args.layers % args.stages == 0, "layers must split evenly"

    cfg_kw = dict(vocab_size=512, n_layer=args.layers, n_head=2,
                  d_model=args.d_model, d_ff=4 * args.d_model,
                  max_seq=args.seq)
    ray_tpu.init(num_cpus=float(max(4, args.stages + 1)))
    stages = [GPTStage.remote(cfg_kw, i, args.stages)
              for i in range(args.stages)]

    with InputNode() as inp:
        node = inp
        for s in stages:
            node = s.fwd.bind(node)
    compiled = node.experimental_compile(
        channel_bytes=64 * 1024 * 1024)

    rng = np.random.default_rng(0)
    tokens = rng.integers(0, 512, size=(args.batch, args.seq),
                          dtype=np.int32)
    # warmup: trace + compile each stage once
    logits = compiled.execute(tokens).get(timeout=600)
    assert logits.shape[:2] == (args.batch, args.seq), logits.shape

    # sustained throughput: keep the pipeline full (one batch in flight
    # per stage) so every stage computes on every tick
    depth = args.stages + 1
    t0 = time.perf_counter()
    inflight = []
    done = 0
    for i in range(args.iters):
        inflight.append(compiled.execute(tokens))
        if len(inflight) >= depth:
            inflight.pop(0).get(timeout=600)
            done += 1
    for r in inflight:
        r.get(timeout=600)
        done += 1
    dt = time.perf_counter() - t0
    toks = args.batch * args.seq * done / dt
    print(f"pipeline: {args.stages} stages x {args.layers} layers, "
          f"{done} iters, {toks:.0f} tokens/s")

    compiled.teardown()
    ray_tpu.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
