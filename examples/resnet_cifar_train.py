"""ResNet-18 data-parallel training (BASELINE: 'ResNet-18/CIFAR-10
2-worker CPU reference'). Synthetic CIFAR-shaped data by default; plug a
real loader through ray_tpu.data and get_dataset_shard."""
import argparse

import numpy as np

import ray_tpu
from ray_tpu import train
from ray_tpu.train import JaxTrainer, RunConfig, ScalingConfig


def train_loop(config):
    import jax
    import jax.numpy as jnp
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ray_tpu.models import ResNet, ResNetConfig

    mesh = train.get_mesh()
    if config.get("full"):
        cfg = ResNetConfig.resnet18_cifar(dtype=jnp.float32)
    else:  # smoke: one block per stage, narrow
        cfg = ResNetConfig(stage_sizes=(1, 1), width=8, dtype=jnp.float32)
    model = ResNet(cfg)
    params, state = model.init(jax.random.PRNGKey(0))
    tx = optax.sgd(0.1, momentum=0.9)
    opt_state = tx.init(params)
    B = config.get("batch", 8)
    data_sharding = NamedSharding(mesh, P(("dp", "fsdp"), None, None, None))

    def loss_fn(params, state, images, labels):
        logits, new_state = model.apply(params, state, images, train=True)
        onehot = jax.nn.one_hot(labels, cfg.num_classes)
        loss = -jnp.mean(jnp.sum(onehot * jax.nn.log_softmax(logits), -1))
        return loss, new_state

    @jax.jit
    def step(params, state, opt_state, images, labels):
        (loss, state), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params, state, images, labels)
        updates, opt_state = tx.update(grads, opt_state, params)
        return loss, optax.apply_updates(params, updates), state, opt_state

    rng = np.random.default_rng(0)
    for i in range(config.get("steps", 3)):
        images = jax.device_put(
            rng.normal(size=(B, 32, 32, 3)).astype(np.float32),
            data_sharding)
        labels = jnp.asarray(rng.integers(0, 10, B))
        loss, params, state, opt_state = step(params, state, opt_state,
                                              images, labels)
        train.report({"loss": float(loss), "step": i})


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--full", action="store_true")
    ap.add_argument("--steps", type=int, default=3)
    args = ap.parse_args()
    ray_tpu.init(num_cpus=8, ignore_reinit_error=True)
    trainer = JaxTrainer(
        train_loop,
        train_loop_config={"full": args.full, "steps": args.steps},
        scaling_config=ScalingConfig(num_workers=2, devices_per_worker=4),
        run_config=RunConfig(name="resnet_cifar"))
    result = trainer.fit()
    assert result.error is None, result.error
    print("final:", result.metrics)


if __name__ == "__main__":
    main()
