"""ViT PBT hyperparameter sweep through Tune (BASELINE: 'ViT-B/16 PBT
sweep on a multi-host v5e slice'). Population-based training: bottom
trials clone top trials' checkpoints and perturb the learning rate."""
import argparse

import numpy as np

import ray_tpu
from ray_tpu import tune
from ray_tpu.tune import PopulationBasedTraining, TuneConfig, Tuner


def train_vit(config):
    import jax
    import jax.numpy as jnp
    import optax

    from ray_tpu.models import ViT, ViTConfig

    cfg = (ViTConfig.b16(num_classes=10, dtype=jnp.float32)
           if config.get("full")
           else ViTConfig.tiny(dtype=jnp.float32))
    model = ViT(cfg)
    ck = tune.get_checkpoint()
    if ck and "params" in ck:
        params = jax.tree.map(jnp.asarray, ck["params"])
        start = int(ck.get("it", 0))
    else:
        params = model.init(jax.random.PRNGKey(0))
        start = 0
    tx = optax.adam(config["lr"])
    opt_state = tx.init(params)
    rng = np.random.default_rng(start)
    B, side = 4, cfg.image_size

    def loss_fn(params, images, labels):
        logits = model.apply(params, images)
        onehot = jax.nn.one_hot(labels, cfg.num_classes)
        return -jnp.mean(jnp.sum(onehot * jax.nn.log_softmax(logits), -1))

    step = jax.jit(jax.value_and_grad(loss_fn))
    for i in range(start, start + config.get("iters", 4)):
        images = rng.normal(size=(B, side, side, 3)).astype(np.float32)
        labels = rng.integers(0, 10, B)
        loss, grads = step(params, jnp.asarray(images), jnp.asarray(labels))
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        tune.report(loss=float(loss), training_iteration=i + 1,
                    checkpoint={"params": jax.device_get(params),
                                "it": i + 1})


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--full", action="store_true")
    ap.add_argument("--population", type=int, default=4)
    args = ap.parse_args()
    ray_tpu.init(num_cpus=8, ignore_reinit_error=True)
    pbt = PopulationBasedTraining(
        perturbation_interval=2,
        hyperparam_mutations={"lr": [1e-4, 3e-4, 1e-3, 3e-3]})
    results = Tuner(
        train_vit,
        param_space={"lr": tune.choice([1e-4, 3e-4, 1e-3, 3e-3]),
                     "full": args.full},
        tune_config=TuneConfig(metric="loss", mode="min",
                               num_samples=args.population,
                               scheduler=pbt)).fit()
    best = results.get_best_result()
    print("best lr:", best.metrics["config"]["lr"],
          "loss:", best.metrics["loss"])


if __name__ == "__main__":
    main()
