"""PPO through the rollout-actor/learner split (BASELINE: 'PPO Atari
Breakout' shape; the built-in vectorized CartPole stands in — register
an Atari VectorEnv via ray_tpu.rllib.register_env for the real thing)."""
import argparse

import numpy as np

import ray_tpu
from ray_tpu.rllib import PPOConfig


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--workers", type=int, default=2)
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--target", type=float, default=150.0)
    args = ap.parse_args()
    ray_tpu.init(num_cpus=max(4, args.workers + 2),
                 ignore_reinit_error=True)
    algo = (PPOConfig()
            .environment("CartPole-v1")
            .rollouts(num_rollout_workers=args.workers,
                      num_envs_per_worker=8,
                      rollout_fragment_length=128)
            .training(lr=1e-3, entropy_coeff=0.005)
            .build())
    try:
        best = 0.0
        for i in range(args.iters):
            r = algo.train()
            if np.isfinite(r["episode_reward_mean"]):
                best = max(best, r["episode_reward_mean"])
            print(f"iter {r['training_iteration']:3d} "
                  f"reward={r['episode_reward_mean']:7.1f} "
                  f"steps/s={r['env_steps_per_sec']:,.0f}")
            if best >= args.target:
                break
        print("best reward:", best)
    finally:
        algo.stop()


if __name__ == "__main__":
    main()
