"""PPO on the Breakout-shaped pixels pipeline — the BASELINE 'PPO Atari
Breakout' configuration: 84x84x4 uint8 observations through the Atari
wrappers (WarpFrame grayscale+resize, FrameStack), a NatureCNN policy on
the learner, numpy conv inference in the rollout actors. This image
ships no ALE/ROMs, so BreakoutShapedVecEnv (native 210x160x3 frames,
Breakout's NOOP/FIRE/RIGHT/LEFT action set, paddle-intercepts-ball
dynamics) stands in; swap the env name for a registered ALE VectorEnv to
run the real ROMs."""
import argparse

import numpy as np

import ray_tpu
from ray_tpu.rllib import PPOConfig


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--workers", type=int, default=2)
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument("--target", type=float, default=3.0)  # catches/episode
    args = ap.parse_args()
    ray_tpu.init(num_cpus=max(4, args.workers + 2),
                 ignore_reinit_error=True)
    algo = (PPOConfig(hidden=(512,))
            .environment("BreakoutShaped-v0")
            .rollouts(num_rollout_workers=args.workers,
                      num_envs_per_worker=4,
                      rollout_fragment_length=64)
            .training(lr=2.5e-4, entropy_coeff=0.01,
                      sgd_minibatch_size=128, num_sgd_epochs=2)
            .build())
    try:
        best = float("-inf")
        for _ in range(args.iters):
            r = algo.train()
            if np.isfinite(r["episode_reward_mean"]):
                best = max(best, r["episode_reward_mean"])
            print(f"iter {r['training_iteration']:3d} "
                  f"reward={r['episode_reward_mean']:6.2f} "
                  f"steps/s={r['env_steps_per_sec']:,.0f}")
            if best >= args.target:
                break
        print("best reward:", best)
    finally:
        algo.stop()


if __name__ == "__main__":
    main()
