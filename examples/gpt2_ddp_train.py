"""GPT-2 DDP training through ray_tpu.train (BASELINE: 'GPT-2-small DDP,
NCCL->ICI allreduce path'). Gang workers share a jax mesh; gradients
allreduce over ICI inside jit — no NCCL, no process groups."""
import argparse

import numpy as np

import ray_tpu
from ray_tpu import train
from ray_tpu.train import JaxTrainer, RunConfig, ScalingConfig


def train_loop(config):
    import jax
    import jax.numpy as jnp
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ray_tpu.models import GPT, GPTConfig

    mesh = train.get_mesh()
    cfg = (GPTConfig.small(dtype=jnp.bfloat16, use_flash=True)
           if config.get("full") else
           GPTConfig.tiny(dtype=jnp.float32, use_flash=False))
    model = GPT(cfg)
    params = jax.jit(model.init,
                     out_shardings=model.param_shardings(mesh))(
        jax.random.PRNGKey(0))
    tx = optax.adamw(3e-4, weight_decay=0.1)
    opt_state = jax.jit(tx.init)(params)
    B, S = config.get("batch", 8), config.get("seq", 64)
    data_sharding = NamedSharding(mesh, P(("dp", "fsdp"), None))

    @jax.jit
    def step(params, opt_state, tokens, targets):
        loss, grads = jax.value_and_grad(model.loss)(params, tokens, targets)
        updates, opt_state = tx.update(grads, opt_state, params)
        return loss, optax.apply_updates(params, updates), opt_state

    rng = np.random.default_rng(0)
    for i in range(config.get("steps", 3)):
        tokens = jax.device_put(
            rng.integers(0, cfg.vocab_size, (B, S)).astype(np.int32),
            data_sharding)
        targets = jnp.roll(tokens, -1, axis=1)
        loss, params, opt_state = step(params, opt_state, tokens, targets)
        train.report({"loss": float(loss), "step": i})


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--full", action="store_true")
    ap.add_argument("--num-workers", type=int, default=2)
    ap.add_argument("--steps", type=int, default=3)
    args = ap.parse_args()
    ray_tpu.init(num_cpus=8, ignore_reinit_error=True)
    trainer = JaxTrainer(
        train_loop,
        train_loop_config={"full": args.full, "steps": args.steps},
        scaling_config=ScalingConfig(num_workers=args.num_workers,
                                     devices_per_worker=4),
        run_config=RunConfig(name="gpt2_ddp"))
    result = trainer.fit()
    assert result.error is None, result.error
    print("final:", result.metrics)


if __name__ == "__main__":
    main()
