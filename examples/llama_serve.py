"""Llama pjit-sharded Serve inference (BASELINE: 'Llama-2-7B pjit-sharded
Serve inference'). A MeshDeployment replica spans a gang of mesh workers;
the model's parameters shard over the mesh per its logical axes and
greedy decode runs jitted with a KV cache. --full uses llama2_7b sizes."""
import argparse

import numpy as np

import ray_tpu
from ray_tpu import serve


def build(mesh, config):
    import jax
    import jax.numpy as jnp

    from ray_tpu.models import Llama, LlamaConfig

    cfg = (LlamaConfig.llama2_7b() if config.get("full")
           else LlamaConfig.tiny(dtype=jnp.float32))
    model = Llama(cfg)
    params = jax.jit(model.init,
                     out_shardings=model.param_shardings(mesh))(
        jax.random.PRNGKey(0))

    @jax.jit
    def greedy_next(params, tokens):
        logits = model.apply(params, tokens)
        return logits[:, -1, :].argmax(-1)

    def apply(params, payload):
        tokens = jnp.asarray(payload["tokens"], jnp.int32)
        out = list(np.asarray(payload["tokens"][0]))
        for _ in range(int(payload.get("max_new", 4))):
            nxt = int(jax.device_get(
                greedy_next(params, jnp.asarray([out], jnp.int32))[0]))
            out.append(nxt)
        return out

    return params, apply


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--full", action="store_true")
    ap.add_argument("--num-workers", type=int, default=2)
    args = ap.parse_args()
    ray_tpu.init(num_cpus=8, ignore_reinit_error=True)
    full = args.full

    @serve.deployment(num_replicas=1, health_check_timeout_s=120)
    class LlamaServer(serve.MeshDeployment):
        def __init__(self):
            super().__init__(build, num_workers=args.num_workers,
                             devices_per_worker=2, config={"full": full})

    handle = serve.run(LlamaServer.bind(), timeout=300)
    out = ray_tpu.get(handle.remote(
        {"tokens": [[1, 5, 9]], "max_new": 4}), timeout=120)
    print("generated token ids:", out)
    serve.shutdown()


if __name__ == "__main__":
    main()
