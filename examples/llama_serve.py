"""Llama pjit-sharded Serve inference (BASELINE: 'Llama-2-7B pjit-sharded
Serve inference'). A MeshDeployment replica spans a gang of mesh workers;
the model's parameters shard over the mesh per its logical axes and
greedy decode runs jitted with a KV cache. --full uses llama2_7b sizes."""
import argparse

import numpy as np

import ray_tpu
from ray_tpu import serve


def build(mesh, config):
    import jax
    import jax.numpy as jnp

    from ray_tpu.models import Llama, LlamaConfig

    cfg = (LlamaConfig.llama2_7b() if config.get("full")
           else LlamaConfig.tiny(dtype=jnp.float32))
    model = Llama(cfg)
    params = jax.jit(model.init,
                     out_shardings=model.param_shardings(mesh))(
        jax.random.PRNGKey(0))
    # KV-cache decode: ONE compiled step with static [B, 1] shapes —
    # no per-token retrace, no prefix recompute
    decode = jax.jit(model.decode_step)

    def apply(params, payload):
        prompt = list(np.asarray(payload["tokens"][0]).tolist())
        cache = model.init_cache(batch=1)
        # prefill the cache one token at a time (static shapes; a batched
        # prefill kernel is the production upgrade)
        logits = None
        for tok in prompt:
            logits, cache = decode(params, cache,
                                   jnp.asarray([[tok]], jnp.int32))
        out = list(prompt)
        for _ in range(int(payload.get("max_new", 4))):
            nxt = int(jax.device_get(logits[0].argmax(-1)))
            out.append(nxt)
            logits, cache = decode(params, cache,
                                   jnp.asarray([[nxt]], jnp.int32))
        return out

    return params, apply


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--full", action="store_true")
    ap.add_argument("--num-workers", type=int, default=2)
    args = ap.parse_args()
    ray_tpu.init(num_cpus=8, ignore_reinit_error=True)
    full = args.full

    @serve.deployment(num_replicas=1, health_check_timeout_s=120)
    class LlamaServer(serve.MeshDeployment):
        def __init__(self):
            super().__init__(build, num_workers=args.num_workers,
                             devices_per_worker=2, config={"full": full})

    handle = serve.run(LlamaServer.bind(), timeout=300)
    out = ray_tpu.get(handle.remote(
        {"tokens": [[1, 5, 9]], "max_new": 4}), timeout=120)
    print("generated token ids:", out)
    serve.shutdown()


if __name__ == "__main__":
    main()
