"""Llama Serve inference (BASELINE: 'Llama-2-7B pjit-sharded Serve
inference') — now on the continuous-batching engine.

Default path: an `LLMServer` deployment (ray_tpu.serve.llm) — paged KV
cache, iteration-level batching, streamed tokens. The driver submits
concurrent prompts through the streaming handle and prints per-request
TTFT (time to first token) plus aggregate decode throughput.

`--no-engine` keeps the legacy path for A/B: a MeshDeployment replica
spanning a gang of mesh workers, full per-request prefill through one
jitted decode step (the pre-engine baseline the BENCH llm_serve row
measures against). --full uses llama2_7b sizes on either path.
"""
import argparse
import os
import threading
import time

import numpy as np

import ray_tpu
from ray_tpu import serve


# ---------------------------------------------------------------------------
# engine path (default)


def run_engine(args) -> None:
    from ray_tpu.serve.llm import LLMServer

    app = serve.deployment(
        num_replicas=1, health_check_timeout_s=120)(LLMServer).bind(
        model="llama2-7b" if args.full else "llama-tiny",
        engine_config={"max_batch": args.concurrency,
                       "num_blocks": 256, "block_size": 16,
                       "max_blocks_per_seq": 16,
                       "prefill_buckets": (16, 32, 64),
                       "tp": args.tp})
    handle = serve.run(app, timeout=300)

    prompts = [[1 + i, 5, 9] for i in range(args.requests)]
    ttfts = [None] * len(prompts)
    outs = [None] * len(prompts)
    t0 = time.perf_counter()

    errors = []

    def client(i: int) -> None:
        try:
            gen = handle.options(stream=True).remote(
                {"tokens": prompts[i], "max_tokens": args.max_new,
                 "stream": True})
            toks = []
            for tok in gen:
                if not toks:
                    ttfts[i] = time.perf_counter() - t0
                toks.append(tok)
            outs[i] = toks
        except Exception as e:  # noqa: BLE001 — surfaced after join
            errors.append((i, e))

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(len(prompts))]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=300)
    wall = time.perf_counter() - t0

    if errors:
        raise RuntimeError(f"streaming clients failed: {errors}")
    failed = [i for i, o in enumerate(outs) if o is None]
    if failed:
        raise RuntimeError(f"clients {failed} timed out")
    total = sum(len(o) for o in outs)
    for i, (p, o) in enumerate(zip(prompts, outs)):
        print(f"req {i}: ttft={ttfts[i] * 1e3:.1f}ms "
              f"generated token ids: {p + o}")
    print(f"aggregate: {total} tokens in {wall:.2f}s "
          f"({total / max(wall, 1e-9):.1f} tok/s, "
          f"concurrency {len(prompts)})")
    stats = ray_tpu.get(handle.stats.remote(), timeout=30)
    print(f"engine stats: {stats}")
    if args.tp > 1:
        # the sharded-serve acceptance surface: one replica spans tp
        # chips, KV pool block-sharded per chip (docs/SHARDING.md);
        # the engine tracks peak occupancy so the fast tiny-model runs
        # still show the resident-block split
        print(f"tp={args.tp} replica mesh — per-chip KV occupancy at "
              f"peak ({stats['kv_blocks_peak']} blocks live):")
        for chip, used in enumerate(
                stats.get("kv_blocks_peak_per_chip", [])):
            byts = stats.get("kv_bytes_per_chip", {}).get(str(chip), "?")
            print(f"  chip {chip}: {used} blocks in use, "
                  f"{byts} cache bytes resident")
    serve.shutdown()


# ---------------------------------------------------------------------------
# legacy path (--no-engine): MeshDeployment, per-request prefill


def build(mesh, config):
    import jax
    import jax.numpy as jnp

    from ray_tpu.models import Llama, LlamaConfig

    cfg = (LlamaConfig.llama2_7b() if config.get("full")
           else LlamaConfig.tiny(dtype=jnp.float32))
    model = Llama(cfg)
    params = jax.jit(model.init,
                     out_shardings=model.param_shardings(mesh))(
        jax.random.PRNGKey(0))
    # KV-cache decode: ONE compiled step with static [B, 1] shapes —
    # no per-token retrace, no prefix recompute
    decode = jax.jit(model.decode_step)

    def apply(params, payload):
        prompt = list(np.asarray(payload["tokens"][0]).tolist())
        cache = model.init_cache(batch=1)
        # prefill the cache one token at a time (static shapes; the
        # engine path's bucketed prefill is the production upgrade)
        logits = None
        for tok in prompt:
            logits, cache = decode(params, cache,
                                   jnp.asarray([[tok]], jnp.int32))
        out = list(prompt)
        for _ in range(int(payload.get("max_new", 4))):
            nxt = int(jax.device_get(logits[0].argmax(-1)))
            out.append(nxt)
            logits, cache = decode(params, cache,
                                   jnp.asarray([[nxt]], jnp.int32))
        return out

    return params, apply


def run_legacy(args) -> None:
    full = args.full

    @serve.deployment(num_replicas=1, health_check_timeout_s=120)
    class LlamaServer(serve.MeshDeployment):
        def __init__(self):
            super().__init__(build, num_workers=args.num_workers,
                             devices_per_worker=2, config={"full": full})

    handle = serve.run(LlamaServer.bind(), timeout=300)
    t0 = time.perf_counter()
    out = ray_tpu.get(handle.remote(
        {"tokens": [[1, 5, 9]], "max_new": args.max_new}), timeout=120)
    wall = time.perf_counter() - t0
    print("generated token ids:", out)
    print(f"full round trip {wall * 1e3:.1f}ms (prefill recomputed "
          f"per request — the engine path amortizes it)")
    serve.shutdown()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--full", action="store_true")
    ap.add_argument("--no-engine", action="store_true",
                    help="legacy MeshDeployment path (A/B baseline)")
    ap.add_argument("--num-workers", type=int, default=2,
                    help="mesh gang size (legacy path)")
    ap.add_argument("--requests", type=int, default=4,
                    help="concurrent streaming clients (engine path)")
    ap.add_argument("--concurrency", type=int, default=4,
                    help="engine max_batch (engine path)")
    ap.add_argument("--max-new", type=int, default=8)
    ap.add_argument("--tp", type=int, default=1,
                    help="tensor-parallel width: the replica's engine "
                         "lowers under a tp-chip mesh (forced host "
                         "devices off-TPU; docs/SHARDING.md)")
    args = ap.parse_args()
    if args.tp > 1 and "--xla_force_host_platform_device_count" not in \
            os.environ.get("XLA_FLAGS", ""):
        # must land in the environment BEFORE any process (driver or
        # replica worker) imports jax: workers inherit it at spawn
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={args.tp}")
    ray_tpu.init(num_cpus=8, ignore_reinit_error=True)
    if args.no_engine:
        run_legacy(args)
    else:
        run_engine(args)


if __name__ == "__main__":
    main()
