"""External-environment serving: a simulator OUTSIDE the cluster (here,
a subprocess with its own CartPole physics and zero ray_tpu imports
beyond the thin HTTP PolicyClient) drives episodes against a policy
server; PPO trains on whatever the clients produce.

ref: rllib/examples/serving/cartpole_server.py + cartpole_client.py.
"""
import argparse
import os
import subprocess
import sys
import tempfile
import time

CLIENT = r'''
import math, sys, time
sys.path.insert(0, sys.argv[3])
from ray_tpu.rllib.policy_client import PolicyClient

def step(s, a):
    x, xd, th, thd = s
    force = 10.0 if a == 1 else -10.0
    costh, sinth = math.cos(th), math.sin(th)
    temp = (force + 0.05 * thd * thd * sinth) / 1.1
    thacc = (9.8 * sinth - costh * temp) / (0.5 * (4/3 - 0.1 * costh**2 / 1.1))
    xacc = temp - 0.05 * thacc * costh / 1.1
    x += 0.02 * xd; xd += 0.02 * xacc; th += 0.02 * thd; thd += 0.02 * thacc
    return [x, xd, th, thd], 1.0, abs(x) > 2.4 or abs(th) > 0.2095

import random
client = PolicyClient(sys.argv[1])
deadline = time.time() + float(sys.argv[2])
rng = random.Random(0)
while time.time() < deadline:
    eid = client.start_episode()
    s = [rng.uniform(-0.05, 0.05) for _ in range(4)]
    done = False
    for t in range(500):
        a = client.get_action(eid, s)
        s, r, done = step(s, a)
        client.log_returns(eid, r)
        if done:
            break
    client.end_episode(eid, None if done else s, truncated=not done)
'''


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--clients", type=int, default=2)
    ap.add_argument("--seconds", type=float, default=120.0)
    ap.add_argument("--target", type=float, default=150.0)
    args = ap.parse_args()

    from ray_tpu.rllib import ExternalPPOConfig

    algo = ExternalPPOConfig(obs_dim=4, num_actions=2,
                             train_batch_size=384, num_sgd_epochs=4,
                             lr=3e-3).build()
    host, port = algo.address
    print(f"policy server listening on http://{host}:{port}")
    with tempfile.NamedTemporaryFile("w", suffix=".py",
                                     delete=False) as f:
        f.write(CLIENT)
        script = f.name
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    procs = [subprocess.Popen(
        [sys.executable, script, f"http://{host}:{port}",
         str(args.seconds), repo]) for _ in range(args.clients)]
    try:
        best, t0 = 0.0, time.time()
        while time.time() - t0 < args.seconds:
            r = algo.train()
            m = r["episode_reward_mean"]
            if m == m:
                best = max(best, m)
            print(f"reward={m:7.1f} best={best:7.1f} "
                  f"steps={r['timesteps_total']}")
            if best >= args.target:
                print("target reached")
                break
    finally:
        for p in procs:
            p.kill()
        algo.stop()


if __name__ == "__main__":
    main()
